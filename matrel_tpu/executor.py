"""Executor — lowers an optimized MatExpr into ONE jitted XLA program.

Reference pipeline (SURVEY.md §3.2): optimized Catalyst plan → physical exec
nodes → RDD DAG → shuffle-bounded Spark stages → per-task BLAS. TPU rebuild:
optimized MatExpr → a single traced function over the leaf arrays, with each
matmul dispatched to its planned strategy (shard_map collective recipe) and
everything else to jnp ops; XLA fuses the elementwise traffic into the
matmuls and schedules the collectives on ICI. The whole post-optimizer
pipeline is one compiled program — no per-stage host round-trips.

Zero-padding invariant: every lowered intermediate is exactly 0 outside its
logical region (padding.py). Ops that would break it (scalar-add, pow≤0,
division, broadcasted add/sub, select fills, join merges) re-mask. Aggregates
mask padding where zeros would change the answer (max/min/avg/count).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from matrel_tpu.config import MatrelConfig, default_config
from matrel_tpu.core import mesh as mesh_lib, padding
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.ir import expr as expr_mod, rules
from matrel_tpu.ir.expr import MatExpr, leaves as expr_leaves
from matrel_tpu.obs import trace as trace_lib
from matrel_tpu.parallel import planner, strategies
from matrel_tpu.resilience import faults as faults_lib
from matrel_tpu.utils.profiling import annotate

Array = jax.Array


def _row_mask(n: int, pn: int) -> Array:
    return (jnp.arange(pn) < n)[:, None]


def _col_mask(m: int, pm: int) -> Array:
    return (jnp.arange(pm) < m)[None, :]


def _mask_to_logical(x: Array, shape: Tuple[int, int]) -> Array:
    """Zero out everything outside the logical region."""
    pn, pm = x.shape
    n, m = shape
    if (pn, pm) == (n, m):
        return x
    return jnp.where(_row_mask(n, pn) & _col_mask(m, pm), x, jnp.zeros((), x.dtype))


def _diag_reduce(d: Array, kind: str) -> Array:
    """sum/count/avg/max/min of a 1-D entry vector — the single
    diagonal-aggregate dispatch shared by the dense diag branch and the
    value-join diag branch (count counts nonzero entries; avg divides
    by that count)."""
    if kind == "sum":
        return jnp.sum(d)
    if kind == "count":
        return jnp.sum(d != 0).astype(d.dtype)
    if kind == "avg":
        c = jnp.sum(d != 0)
        return jnp.where(c > 0, jnp.sum(d) / c, 0.0).astype(d.dtype)
    if kind == "max":
        return jnp.max(d)
    if kind == "min":
        return jnp.min(d)
    raise NotImplementedError(kind)


class Lowerer:
    """Recursively lowers MatExpr nodes to jnp ops over padded arrays."""

    def __init__(self, mesh: Mesh, config: MatrelConfig,
                 op_hook: Optional[Callable] = None):
        self.mesh = mesh
        self.config = config
        # analyze-mode per-op wall-clock hook: callable(node, label,
        # seconds), invoked after each node's lowering completes WITH a
        # device sync. Only meaningful when the lowered function runs
        # EAGERLY (obs/analyze.py) — inside a jit trace a perf_counter
        # around tracing measures nothing, so compile_expr never sets
        # it; the hot path stays sync-free (obs_level contract).
        self.op_hook = op_hook
        # layout/dtype memos for the staged-reshard lowering (budget
        # > 0 only): infer_layout/infer_dtype walks at trace time stay
        # O(nodes) across a plan's matmuls (the annotate-pass idiom)
        self._lay_memo: Dict[int, str] = {}
        self._dt_memo: Dict[int, object] = {}
        # id(plan) -> (plan, measured SpMV executor variant "compact" |
        # "expanded"), populated at compile time by the autotune loop
        # (parallel/autotune.lookup_or_measure_spmv); empty = hand
        # defaults decide. The entry CARRIES the plan object and reads
        # validate it by identity (VERDICT r4 "what's weak" #3): a bare
        # id key could misroute a recycled address after the original
        # plan is garbage-collected; the held reference both prevents
        # that collection and proves the match.
        self.spmv_choice: Dict[int, Tuple[object, str]] = {}

    def _spmv_forced(self, plan) -> Optional[str]:
        """The measured executor variant forced for THIS plan object, or
        None. The identity check is the point: an id-keyed hit whose
        stored plan is a different object (the original was collected
        and its address recycled) is a stale entry, not a choice."""
        entry = self.spmv_choice.get(id(plan))
        return entry[1] if entry is not None and entry[0] is plan else None

    def lower(self, root: MatExpr, leaf_order: List[MatExpr]) -> Callable:
        multi = self.lower_multi((root,), leaf_order)

        def fn(*leaf_arrays: Array) -> Array:
            return multi(*leaf_arrays)[0]

        return fn

    def lower_multi(self, roots, leaf_order: List[MatExpr]) -> Callable:
        """Lower several roots into ONE traced function with a SHARED memo:
        common subexpressions (by node identity) are computed once — e.g.
        XᵀX and Xᵀy of the normal equations share the Xᵀ resharding."""
        leaf_pos = {l.uid: i for i, l in enumerate(leaf_order)}

        def fn(*leaf_arrays: Array):
            memo: Dict[int, Array] = {}
            # analyze-mode bookkeeping: _eval recurses through ev, so a
            # node's wall-clock window CONTAINS its children's — track
            # child time per frame and report the EXCLUSIVE remainder
            # (otherwise a depth-N tree reports ~N× the real runtime)
            child_time = []

            def ev(node: MatExpr) -> Array:
                if node.uid in memo:
                    return memo[node.uid]
                # annotate() per physical operator: the profiler-timeline
                # visibility the reference gets from Spark stage names
                # (SURVEY.md §5 "Tracing / profiling"). EVERY node
                # lowering dispatch must go through this one wrapped
                # call — tests/test_obs.py structurally enforces it, so
                # new ops can't silently skip instrumentation. A fused
                # region (ir/fusion.py stamp, config.fusion_enable) is
                # ONE dispatch: the whole member set lowers under this
                # single frame — that per-edge dispatch collapse is the
                # point of the fusion pass.
                sig = (node.attrs.get("fused_region")
                       if self.config.fusion_enable else None)
                if sig is not None:
                    label = f"fused:{sig}"
                else:
                    label = node.kind
                    if node.kind == "matmul":
                        label += ":" + node.attrs.get("strategy", "xla")
                        tier = node.attrs.get("precision_tier")
                        if tier is not None:    # tiered lowering: the
                            label += f"@{tier}"  # per-op label says so
                if self.op_hook is not None:
                    child_time.append(0.0)
                    t0 = time.perf_counter()  # matlint: disable=ML006 analyze-mode op_hook measurement — lands in analyze events
                # fault site "lower": the resilience harness's hook at
                # this ONE dispatch point (fires at trace time — a
                # compile-path fault). Free when fault_inject is "".
                faults_lib.check("lower", self.config)
                with annotate(f"matrel.{label}"):
                    if sig is not None:
                        out = self._eval_region(node, ev, leaf_arrays,
                                                leaf_pos)
                    else:
                        out = self._eval(node, ev, leaf_arrays,
                                         leaf_pos)
                if self.op_hook is not None:
                    # the ONE sanctioned lowering-path sync: analyze
                    # mode only (op_hook is never set on the hot path —
                    # compile_expr leaves it None; obs/analyze.py sets
                    # it for eager per-op wall-clocking)
                    jax.block_until_ready(out)  # matlint: disable=ML001 analyze-mode op_hook
                    dt = time.perf_counter() - t0  # matlint: disable=ML006 analyze-mode op_hook measurement
                    spent_in_children = child_time.pop()
                    if child_time:
                        child_time[-1] += dt
                    self.op_hook(node, label,
                                 max(dt - spent_in_children, 0.0))
                memo[node.uid] = out
                return out

            outs = []
            for root in roots:
                out = ev(root)
                pshape = padding.padded_shape(root.shape, self.mesh)
                if tuple(out.shape) != pshape:
                    out = jnp.pad(out, ((0, pshape[0] - out.shape[0]),
                                        (0, pshape[1] - out.shape[1])))
                if self.config.reshard_peak_budget_bytes > 0:
                    # the ROOT canonical re-lay through the staged
                    # reshard path too (a bmm root's row/col → 2d move
                    # — the _root_reshard_cost leg, made explicit and
                    # per-kind-annotated); the constraint below then
                    # finds the layout already canonical
                    out = self._stage_root_relay(root, out)
                outs.append(jax.lax.with_sharding_constraint(
                    out, padding.canonical_sharding(pshape, self.mesh)))
            return tuple(outs)

        return fn

    # -- per-node lowering --------------------------------------------------

    def _eval(self, node: MatExpr, ev, leaf_arrays, leaf_pos) -> Array:
        k = node.kind
        if k == "leaf":
            return leaf_arrays[leaf_pos[node.uid]]
        if k == "sparse_leaf":
            # densify when a sparse matrix is used outside a matmul; the
            # SpMM fast path handles the matmul case below
            return node.attrs["matrix"].to_dense(self.config).data
        if k == "coo_leaf":
            # same densify fallback for element-sparse leaves; matmuls
            # take the one-hot SpMV path in _matmul
            return node.attrs["matrix"].to_block(self.mesh,
                                                 self.config).data
        if k == "transpose":
            return ev(node.children[0]).T
        if k == "matmul":
            return self._matmul(node, ev)
        if k == "solve":
            return self._solve(node, ev)
        if k == "inverse":
            return self._inverse(node, ev)
        if k == "elemwise":
            return self._elemwise(node, ev)
        if k == "scalar":
            return self._scalar(node, ev)
        if k == "agg":
            return self._agg(node, ev)
        if k == "vec":
            return self._vec(node, ev)
        if k == "rank1":
            a, u, v = (ev(c) for c in node.children)
            return a + u @ v.T
        if k == "select_value":
            x = ev(node.children[0])
            pred, fill = node.attrs["predicate"], node.attrs["fill"]
            out = jnp.where(pred(x), x, jnp.asarray(fill, x.dtype))
            if fill != 0.0:
                out = _mask_to_logical(out, node.shape)
            return out
        if k == "select_index":
            return self._select_index(node, ev)
        if k == "join_index":
            a, b = ev(node.children[0]), ev(node.children[1])
            out = node.attrs["merge"](a, b)
            return _mask_to_logical(out, node.shape)
        if k == "join_value":
            return self._join_value(node, ev)
        if k == "select_block":
            x = ev(node.children[0])
            bs = node.attrs["block_size"]
            pred = node.attrs["predicate"]
            pn, pm = x.shape
            bi = (jnp.arange(pn) // bs)[:, None]
            bj = (jnp.arange(pm) // bs)[None, :]
            return jnp.where(pred(bi, bj), x, jnp.zeros((), x.dtype))
        if k in ("join_rows", "join_cols"):
            return self._join_axis(node, ev)
        raise NotImplementedError(f"lowering for node kind {k!r}")

    def _eval_region(self, root: MatExpr, ev, leaf_arrays,
                     leaf_pos) -> Array:
        """Lower one FUSED REGION (ir/fusion.py stamp) as a single
        dispatch: every member lowers inside the caller's ONE
        ``annotate()`` frame; region INPUTS (non-member children) go
        back through the outer ``ev`` and keep their own frames. The
        member chain ABOVE the anchor matmul is composed into an
        epilogue callable and pushed into the producing kernel's
        epilogue slot (strategies.run_matmul / ops/spmm.apply /
        ops/spgemm.apply_dense → the kernel-registry hook), so XLA
        sees the whole segment as the contraction's epilogue. Member
        lowerings are byte-for-byte the staged ``_eval`` paths —
        every re-mask of the zero-padding invariant runs exactly
        where the staged path runs it (MV111's remask census)."""
        from matrel_tpu.ir import fusion as fusion_lib
        members = fusion_lib.region_nodes(root)
        anchor_uid = root.attrs.get("fused_anchor")

        def make_lev(env: Dict[int, Array]):
            """ONE member evaluator for both the region body and the
            epilogue closure — member-lowering semantics must never
            diverge between the two (the MV111 byte-for-byte
            invariant)."""

            def lev(n: MatExpr) -> Array:
                out = env.get(n.uid)
                if out is not None:
                    return out
                if n.uid not in members:
                    out = ev(n)          # region input: its own frame
                else:
                    out = self._eval(n, lev, leaf_arrays, leaf_pos)  # fused-region member — lowers under the single annotate frame opened by ev
                env[n.uid] = out
                return out

            return lev

        env: Dict[int, Array] = {}
        lev = make_lev(env)
        anchor = members.get(anchor_uid) if anchor_uid is not None \
            else None
        if anchor is None or anchor.uid == root.uid:
            return lev(root)

        def epilogue(x: Array) -> Array:
            env2 = dict(env)
            env2[anchor.uid] = x
            return make_lev(env2)(root)

        epi_ew = fusion_lib.epilogue_elementwise_chain(
            root, members, anchor.uid)
        # the anchor's lowering consumes the epilogue: its output IS
        # the region root's value (operand prologues below the anchor
        # lower through lev when the anchor evaluates its children)
        return self._matmul(anchor, lev, epilogue=epilogue,
                            epilogue_elementwise=epi_ew)

    def _solve(self, node: MatExpr, ev) -> Array:
        """X = A⁻¹·B as a dense solve on the LOGICAL shapes — LU by
        default, Cholesky when attrs["assume"] == "pos" (caller asserts
        SPD; a non-SPD lhs under "pos" yields NaNs, not the LU answer).

        Padded rows/cols must be sliced off first — a zero-padded square
        matrix is singular. Like the reference's normal-equations
        workload, this is a local (replicated) solve intended for
        small/medium systems (e.g. the k×k Gram matrix); it is not a
        distributed triangular solve. Computed in f32 for stability,
        cast back when keep_input_dtype asks for it."""
        l, r = node.children
        n = l.shape[0]
        m = r.shape[1]
        a = ev(l)[:n, :n]
        b = ev(r)[:n, :m]
        if node.attrs.get("assume") == "pos":
            c, low = jax.scipy.linalg.cho_factor(a.astype(jnp.float32))
            out = jax.scipy.linalg.cho_solve((c, low),
                                             b.astype(jnp.float32))
        else:
            out = jnp.linalg.solve(a.astype(jnp.float32),
                                   b.astype(jnp.float32))
        if self.config.keep_input_dtype and a.dtype == b.dtype:
            out = out.astype(a.dtype)
        return self._pad_to_node(out, node)

    def _inverse(self, node: MatExpr, ev) -> Array:
        """A⁻¹ on the logical shape (see _solve for the padding/dtype
        contract). Prefer solve(A, B) — R7 rewrites A⁻¹·B into it."""
        (c,) = node.children
        n = c.shape[0]
        a = ev(c)[:n, :n]
        out = jnp.linalg.inv(a.astype(jnp.float32))
        if self.config.keep_input_dtype:
            out = out.astype(a.dtype)
        return self._pad_to_node(out, node)

    def _join_axis(self, node: MatExpr, ev) -> Array:
        """Row/col-index joins: statically-shaped pairwise merge along the
        non-join axis (the replication-scheme joins of the reference).
        The planner's attrs['replicate'] (choose_join_scheme) picks the
        scheme: "left"/"right" replicate that operand across the mesh
        (the other keeps its sharding); "align" replicates NOTHING —
        both operands are constrained 1D-sharded along the join axis so
        the pairwise merge computes shard-locally (v3 layout credit)."""
        out_entries = node.shape[0] * node.shape[1]
        cap = self.config.join_pair_cap_entries
        if out_entries > cap:
            raise ValueError(
                f"row/col join output has {node.shape[0]}x"
                f"{node.shape[1]} = {out_entries} entries (> "
                f"join_pair_cap_entries = {cap}); select/aggregate the "
                f"operands first or raise the cap in MatrelConfig.")
        l, r = node.children
        a = ev(l)[: l.shape[0], : l.shape[1]]
        b = ev(r)[: r.shape[0], : r.shape[1]]
        rep = node.attrs.get("replicate")
        if rep is not None and self.mesh.size > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P
            repl = NamedSharding(self.mesh, P(None, None))
            if rep == "left":
                a = jax.lax.with_sharding_constraint(a, repl)
            elif rep == "right":
                b = jax.lax.with_sharding_constraint(b, repl)
            else:  # align
                axes = tuple(self.mesh.axis_names)
                spec = (P(axes, None) if node.kind == "join_rows"
                        else P(None, axes))
                sh = NamedSharding(self.mesh, spec)
                a = jax.lax.with_sharding_constraint(a, sh)
                b = jax.lax.with_sharding_constraint(b, sh)
        merge = node.attrs["merge"]
        if node.kind == "join_rows":
            out = merge(a[:, :, None], b[:, None, :])       # (n, ma, mb)
            out = out.reshape(l.shape[0], l.shape[1] * r.shape[1])
        else:
            out = merge(a[:, None, :], b[None, :, :])       # (na, nb, m)
            out = out.reshape(l.shape[0] * r.shape[0], l.shape[1])
        pshape = padding.padded_shape(node.shape, self.mesh)
        if tuple(out.shape) != pshape:
            out = jnp.pad(out, ((0, pshape[0] - out.shape[0]),
                                (0, pshape[1] - out.shape[1])))
        return out

    def _pad_to_node(self, out: Array, node: MatExpr) -> Array:
        pshape = padding.padded_shape(node.shape, self.mesh)
        return jnp.pad(out, ((0, pshape[0] - out.shape[0]),
                             (0, pshape[1] - out.shape[1])))

    def _coo_spmv_stack(self, plan, vectors) -> Array:
        """SpMV results for a sequence of input vectors (columns of the
        dense operand) as a (n_rows, k) array; plan tables ride the
        trace as constants (hoisted into call-time args by
        _hoist_large_consts). On real TPU the compact-table Pallas
        executor runs — faster, and the expanded one-hot tables are
        never built (17× less HBM); CPU keeps the expanded XLA path.
        Single vectors take the matvec kernel; wider stacks the k-wide
        SpMM (one shared gather for all columns)."""
        from matrel_tpu.config import pallas_enabled, pallas_interpret_mode
        from matrel_tpu.ops import spmv as spmv_lib
        use_pallas = pallas_enabled(self.config)
        choice = self._spmv_forced(plan)
        if choice == "expanded":
            # measured: the expanded XLA one-hot path beats the compact
            # Pallas scatter for this plan shape class on this backend
            use_pallas = False
        if use_pallas:
            from matrel_tpu.ops import pallas_spmv as pc
            interp = pallas_interpret_mode(self.config)
            static = (plan.n_rows, plan.n_cols, plan.block, spmv_lib.LO)
            if self.mesh.size == 1:
                tables = pc.compact_tables(plan)
                if len(vectors) == 1:
                    return pc.compact_apply(static, tables, plan.overflow,
                                            vectors[0],
                                            interpret=interp)[:, None]
                return pc.compact_matmat_apply(
                    static, tables, plan.overflow,
                    jnp.stack(vectors, axis=1), interpret=interp)
            # multi-device: pallas_call has no SPMD partitioning rule,
            # but shard_map hands it per-device shapes — row-decompose
            # the compact tables over the mesh and run the scatter on
            # each device's block slice (13 B/slot everywhere; the
            # expanded ~224 B/slot XLA tables are never built).
            return self._coo_compact_sharded(pc, plan, static, vectors,
                                             interp)
        if self.mesh.size > 1:
            # replicate the (small) input vectors before the expanded
            # one-hot contraction. A vector sliced from a 2D-sharded
            # operand arrives PARTIALLY sharded (e.g. P('y',) on a
            # (2, 4) mesh) and this container's jax 0.4.37 GSPMD
            # partitioner miscompiles the gather/one-hot contraction
            # over such inputs: every result entry comes out scaled by
            # exactly gx (the unsharded mesh axis), eager and jitted
            # alike — the pre-existing "COO DSL 2x-scale" failure pair
            # and fuzz[49], root-caused round 6. The compact sharded
            # path replicates x by in_spec already; this pins the same
            # contract on the XLA path. Vectors are SpMV inputs —
            # n_cols floats — so the reshard is noise next to the
            # gather it feeds.
            from jax.sharding import NamedSharding, PartitionSpec as P
            repl = NamedSharding(self.mesh, P())
            vectors = [jax.lax.with_sharding_constraint(v, repl)
                       for v in vectors]
        static = (plan.n_rows, plan.n_cols, plan.block)
        arrays = plan.arrays()
        if len(vectors) == 1:
            return spmv_lib.spmv_apply(static, arrays, vectors[0])[:, None]
        X = jnp.stack(vectors, axis=1)
        extra = plan.spmm_extra(arrays)   # reuse the staged expansion
        # ≤64-column chunks bound the (B, C, k) gather/weight
        # intermediates, matching spmv.spmm's col_chunk
        parts = [spmv_lib.spmm_apply(static, arrays, extra,
                                     X[:, j:j + 64])
                 for j in range(0, X.shape[1], 64)]
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts,
                                                                axis=1)

    def _coo_compact_sharded(self, pc, plan, static, vectors,
                             interp: bool) -> Array:
        """Compact-table SpMV/SpMM inside the executor's traced program
        on a multi-device mesh: shard_map over the mesh with the tables
        row-decomposed per device (shard_compact_tables), dense operand
        replicated, one tiled all_gather of the result. The sharded
        tables ride the trace as committed device arrays and are hoisted
        into call-time args by _hoist_large_consts like any other
        payload constant."""
        from matrel_tpu.utils.compat import shard_map
        from jax.sharding import PartitionSpec as P
        tables = pc.shard_compact_tables(plan, self.mesh)
        axes = tuple(self.mesh.axis_names)
        ov = plan.overflow
        wide = len(vectors) > 1
        x = (jnp.stack(vectors, axis=1) if wide else vectors[0]).astype(
            jnp.float32)

        def kern(src8, lane, off, val, xx, *ovv):
            apply = (pc.compact_sharded_matmat_apply if wide
                     else pc.compact_sharded_apply)
            return apply(static, (src8, lane, off, val), ovv, xx, axes,
                         interpret=interp)

        sm = shard_map(kern, mesh=self.mesh,
                       in_specs=pc.compact_sharded_specs(axes, len(ov)),
                       out_specs=P(), check_vma=False)
        out = sm(*tables, x, *ov)
        return out if wide else out[:, None]

    @staticmethod
    def _same_operand(u: MatExpr, v: MatExpr) -> bool:
        """Do two expression nodes denote the SAME evaluated operand?
        True for a shared DAG node, or for distinct leaf wrappers of
        one matrix object (the DSL creates a fresh leaf per .expr())."""
        if u is v or u.uid == v.uid:
            return True
        return (u.kind == "leaf" and v.kind == "leaf"
                and u.attrs["matrix"] is v.attrs["matrix"])

    def _as_block_sparse(self, leaf_node: MatExpr, bs: int):
        """The BlockSparseMatrix form of an S×S matmul operand:
        sparse_leaf carries one already; coo_leaf is BUCKETED into
        block-granular tiles (never densified — only touched tiles
        materialise), memoised on the matrix per (block_size, mesh)."""
        m = leaf_node.attrs["matrix"]
        if leaf_node.kind == "sparse_leaf":
            return m
        from matrel_tpu.core.sparse import BlockSparseMatrix
        memo = getattr(m, "_block_sparse_memo", None)
        if memo is not None and memo[0] == bs and memo[1] is self.mesh:
            return memo[2]
        # eager even when the cache miss happens inside an outer trace:
        # the conversion builds committed device arrays that must stay
        # static metadata, not tracers (the spmm transpose-memo lesson)
        with jax.ensure_compile_time_eval():
            S = BlockSparseMatrix.from_coo_arrays(
                m.rows, m.cols, m.vals, m.shape, block_size=bs,
                mesh=self.mesh, config=self.config, dtype="float32")
        m._block_sparse_memo = (bs, self.mesh, S)
        return S

    def _spgemm(self, node: MatExpr, epilogue=None,
                epilogue_elementwise: bool = False) -> Array:
        """S×S below the density crossover: tile-intersection SpGEMM —
        neither operand is densified (ops/spgemm.py); the product is
        scattered to the padded dense canonical layout every consumer
        expects (apply_dense pads to padded_shape(node.shape, mesh) —
        the same pair this lowering's consumers compute). The KERNEL
        comes from the planner's ``spgemm_kernel`` stamp (registry
        dispatch — MV110 verifies it); an unstamped node (direct
        execute of a hand-built tree) asks the shared chooser
        itself, so the two can never drift."""
        from matrel_tpu.ops import spgemm as spgemm_lib
        bs = _spgemm_block_size(node, self.config)
        SA = self._as_block_sparse(node.children[0], bs)
        SB = self._as_block_sparse(node.children[1], bs)
        kid = node.attrs.get("spgemm_kernel")
        if kid is None:
            kid, _, _ = spgemm_kernel_choice(node, self.config,
                                             self.mesh)
        return spgemm_lib.apply_dense(
            SA, SB, self.config, kernel=kid, epilogue=epilogue,
            epilogue_elementwise=epilogue_elementwise)

    def _matmul(self, node: MatExpr, ev, epilogue=None,
                epilogue_elementwise: bool = False) -> Array:
        """``epilogue`` is the fused-region slot (ir/fusion.py): a
        callable applied to THIS matmul's canonical output inside the
        same traced region — the staged consumer chain pushed into the
        producing contraction. Dense strategies, SpMM and SpGEMM
        consume it through their own epilogue slots; every other
        dispatch applies it to the branch's finished output (``fin``),
        so fused and staged lowerings are numerically identical."""
        fin = (lambda out: out) if epilogue is None else epilogue
        l, r = node.children
        # S×S (block-sparse AND element-sparse leaves, any mix): the
        # tile-intersection SpGEMM when the ESTIMATED output block
        # density sits below the crossover — above it the densify
        # fallthrough below wins on MXU throughput. ONE dispatch
        # predicate (_spgemm_dispatch) shared with the planner's
        # pricing/layout/decision readers so they can never drift.
        if _spgemm_dispatch(node, self.config):
            return self._spgemm(node, epilogue=epilogue,
                                epilogue_elementwise=epilogue_elementwise)
        # coo_leaf matmuls: per-column one-hot SpMV for narrow dense
        # operands; wide ones (or refused plans) densify — at that point
        # the MXU over a dense block layout beats serialized matvecs.
        # The dispatch predicate is shared with the autotune walk
        # (_coo_dispatch_plan) so the two can never drift.
        if l.kind == "coo_leaf":
            A, k = l.attrs["matrix"], r.shape[1]
            plan = _coo_dispatch_plan(node)
            if plan is None:
                blk = A.to_block(self.mesh, self.config).data
                return strategies.run_matmul("xla", blk, ev(r), self.mesh,
                                             self.config,
                                             epilogue=epilogue)
            dense = ev(r)
            out = self._coo_spmv_stack(
                plan, [dense[: A.shape[1], j] for j in range(k)])
            return fin(self._pad_to_node(out, node))
        if r.kind == "coo_leaf":
            # A·S = (Sᵀ·Aᵀ)ᵀ — use the original matrix's cached
            # transpose plan (_get_plan_t), built at most once
            S, k = r.attrs["matrix"], l.shape[0]
            plan = _coo_dispatch_plan(node)
            if plan is None:
                blk = S.to_block(self.mesh, self.config).data
                return strategies.run_matmul("xla", ev(l), blk, self.mesh,
                                             self.config,
                                             epilogue=epilogue)
            a = ev(l)
            out = self._coo_spmv_stack(
                plan, [a[i, : l.shape[1]] for i in range(k)]).T
            return fin(self._pad_to_node(out, node))
        if l.kind == "sparse_leaf":
            from matrel_tpu.ops import spmm as spmm_lib
            return spmm_lib.apply(l.attrs["matrix"], ev(r), r.shape,
                                  self.config, epilogue=epilogue)
        if r.kind == "sparse_leaf" and l.kind != "sparse_leaf":
            # A·S = (Sᵀ·Aᵀ)ᵀ — transpose the tile stack once, EAGERLY:
            # this code runs inside the executor's trace, and a traced
            # transpose()/device_put would turn the matrix's static tile
            # metadata into tracers (the SpMM builder reads it on host).
            from matrel_tpu.ops import spmm as spmm_lib
            S = r.attrs["matrix"]
            st = getattr(S, "_transposed_memo", None)
            if st is None:
                with jax.ensure_compile_time_eval():
                    st = S.transpose()
                S._transposed_memo = st
            at = ev(l).T
            out = spmm_lib.apply(st, at, (l.shape[1], l.shape[0]),
                                 self.config)
            return fin(out.T)
        gram = None
        if l.kind == "transpose" and self._same_operand(l.children[0], r):
            gram = ("AtA", r)
        elif r.kind == "transpose" and self._same_operand(r.children[0], l):
            gram = ("AAt", l)
        # a stamped precision tier OWNS the matmul's numerics — the
        # config-level matmul_precision="high" gram shortcut must not
        # second-guess it (the tier path below emits its own passes)
        if node.attrs.get("precision_tier") is not None:
            gram = None
        if gram is not None and self.config.matmul_precision == "high":
            side, base = gram
            x = ev(base)
            if x.dtype == jnp.float32:
                # symmetric 2-pass bf16 split for AᵀA / AAᵀ under
                # precision="high": of XLA's three bf16x3 products
                # (hi·hi, hi·lo, lo·hi) the cross terms are transposes
                # of each other in a Gram, so one MXU pass is a k×k
                # transpose instead — 33% fewer matmul FLOPs at
                # identical accuracy (same three products; round-3
                # floor analysis, docs/ROUND3.md). XLA's generic dot
                # cannot apply this: it does not know both operands
                # are the same matrix. The transpose operand is never
                # materialised either.
                from matrel_tpu.ops.gram import symmetric_gram
                strategy = node.attrs.get("strategy", "xla")
                if side == "AtA":
                    mm = lambda p, q: strategies.run_matmul(
                        strategy, p.T, q, self.mesh, self.config)
                else:                    # A·Aᵀ
                    mm = lambda p, q: strategies.run_matmul(
                        strategy, p, q.T, self.mesh, self.config)
                return fin(symmetric_gram(x, mm).astype(jnp.float32))
        a, b = ev(node.children[0]), ev(node.children[1])
        strategy = node.attrs.get("strategy", "xla")
        if self.config.reshard_peak_budget_bytes > 0:
            # staged reshard lowering (parallel/reshard.py): re-lay
            # each operand to the layout the strategy consumes through
            # the compiled peak-bounded step sequence — explicit
            # per-step collectives under per-kind annotate labels —
            # instead of whatever one-shot move XLA would emit from
            # the shard_map in_spec. Off (the default) this branch
            # constructs nothing and the lowering is bit-identical.
            a, b = self._stage_matmul_operands(node, a, b)
        tier = node.attrs.get("precision_tier")
        if tier is not None and tier != "f32":
            # precision-tiered execution (ops/precision.py): the
            # multi-pass decomposition runs every pass through the SAME
            # stamped strategy recipe, so tiering composes with the
            # distribution plan. Dispatch stays at this one site — the
            # annotate() wrapper above already labels it. The tier owns
            # the output dtype (int tiers keep their exact int32
            # accumulator; bf16 tiers return the f32 accumulation), so
            # the keep_input_dtype cast below does not apply.
            from matrel_tpu.ops import precision as precision_lib
            mm = lambda p, q: strategies.run_matmul(
                strategy, p, q, self.mesh, self.config)
            return fin(precision_lib.tiered_matmul(tier, a, b, mm))

        def storage_epi(out: Array) -> Array:
            # the keep_input_dtype storage cast composes BEFORE the
            # fused epilogue, so the epilogue chain sees exactly the
            # value the staged consumer would (bit-identical numerics
            # between fused and staged lowerings)
            if (self.config.keep_input_dtype and a.dtype == b.dtype
                    and out.dtype != a.dtype):
                out = out.astype(a.dtype)
            return fin(out)

        return strategies.run_matmul(strategy, a, b, self.mesh,
                                     self.config, epilogue=storage_epi)

    def _stage_root_relay(self, root: MatExpr, out: Array) -> Array:
        """Root output → canonical 2d through the compiled reshard
        steps (budget > 0 only; vectors and indivisible shapes keep
        the legacy constraint). The derivation is
        ``reshard.root_relay_plan`` — shared with MV109, which is the
        layer that BLOCKS an over-budget root move pre-trace
        (verify_plans="error"); the lowering itself still applies the
        min-peak plan, which is never worse than the one-shot move."""
        from matrel_tpu.parallel import reshard as reshard_lib
        plan = reshard_lib.root_relay_plan(root, self.mesh, self.config,
                                           self._lay_memo,
                                           self._dt_memo)
        if plan is None:
            return out
        return reshard_lib.apply_staged(out, plan, self.mesh)

    def _stage_matmul_operands(self, node: MatExpr, a: Array,
                               b: Array) -> Tuple[Array, Array]:
        """Apply the staged ReshardPlans of a dense matmul's operand
        re-lays (reshard.staged_matmul_moves — the ONE derivation
        shared with matmul_decisions and MV109). With autotune on, a
        MEASURED "naive" winner for the move's shape class skips the
        staging (the closed measurement loop overrules the model, the
        matmul-strategy contract)."""
        from matrel_tpu.parallel import reshard as reshard_lib
        moves = reshard_lib.staged_matmul_moves(
            node, self.mesh, self.config, self._lay_memo, self._dt_memo)
        arrs = [a, b]
        for i, plan in moves:
            if self.config.autotune:
                from matrel_tpu.parallel import autotune
                choice = autotune.lookup_or_measure_reshard(
                    plan, self.mesh, self.config)
                if choice == "naive":
                    continue
            arrs[i] = reshard_lib.apply_staged(arrs[i], plan, self.mesh)
        return arrs[0], arrs[1]

    def _elemwise(self, node: MatExpr, ev) -> Array:
        l, r = node.children
        a, b = ev(l), ev(r)
        broadcast = l.shape != r.shape
        if broadcast:
            # slice logical size-1 dims so padded shapes broadcast correctly
            a = self._slice_for_broadcast(a, l.shape, node.shape)
            b = self._slice_for_broadcast(b, r.shape, node.shape)
        op = node.attrs["op"]
        if op == "add":
            out = a + b
        elif op == "sub":
            out = a - b
        elif op == "mul":
            out = a * b
        elif op == "div":
            safe_b = jnp.where(b == 0, jnp.ones((), b.dtype), b)
            out = jnp.where(b == 0, jnp.zeros((), jnp.result_type(a, b)),
                            a / safe_b)
        elif op == "min":
            out = jnp.minimum(a, b)
        elif op == "max":
            out = jnp.maximum(a, b)
        else:
            raise NotImplementedError(op)
        if broadcast and op != "mul":
            out = _mask_to_logical(out, node.shape)
        return out

    @staticmethod
    def _slice_for_broadcast(x: Array, lshape, out_shape) -> Array:
        if lshape[0] == 1 and out_shape[0] != 1 and x.shape[0] != 1:
            x = x[:1, :]
        if lshape[1] == 1 and out_shape[1] != 1 and x.shape[1] != 1:
            x = x[:, :1]
        return x

    def _scalar(self, node: MatExpr, ev) -> Array:
        x = ev(node.children[0])
        op, v = node.attrs["op"], node.attrs["value"]
        if op == "mul":
            return x * jnp.asarray(v, x.dtype)
        if op == "add":
            out = x + jnp.asarray(v, x.dtype)
            return _mask_to_logical(out, node.shape) if v != 0.0 else out
        if op == "pow":
            out = jnp.power(x, jnp.asarray(v, x.dtype))
            return _mask_to_logical(out, node.shape) if v <= 0 else out
        raise NotImplementedError(op)

    def _agg(self, node: MatExpr, ev) -> Array:
        (child,) = node.children
        if child.kind == "join_value":
            # never materialise the pair matrix under an aggregate —
            # stream it (sort-based or chunked; value_join.py)
            return self._agg_join_value(node, child, ev)
        x = ev(child)
        kind, axis = node.attrs["agg"], node.attrs["axis"]
        n, m = child.shape
        pn, pm = x.shape
        if axis == "diag":
            d = jnp.diagonal(x)[:n]
            return _diag_reduce(d, kind).reshape(1, 1).astype(x.dtype)
        ax = {"row": 1, "col": 0, "all": None}[axis]

        def finish(res: Array) -> Array:
            if axis == "row":
                return res.reshape(pn, 1) if res.ndim == 1 else res
            if axis == "col":
                return res.reshape(1, pm) if res.ndim == 1 else res
            return res.reshape(1, 1)

        if kind == "sum":
            out = finish(jnp.sum(x, axis=ax))
        elif kind == "count":
            out = finish(jnp.sum((x != 0), axis=ax).astype(x.dtype))
        elif kind == "avg":
            s = jnp.sum(x, axis=ax)
            c = jnp.sum((x != 0), axis=ax)
            out = finish(jnp.where(c > 0, s / c, 0).astype(x.dtype))
        elif kind in ("max", "min"):
            fill = -jnp.inf if kind == "max" else jnp.inf
            valid = _row_mask(n, pn) & _col_mask(m, pm)
            masked = jnp.where(valid, x, jnp.asarray(fill, x.dtype))
            red = jnp.max if kind == "max" else jnp.min
            out = finish(red(masked, axis=ax))
            out = jnp.where(jnp.isfinite(out), out, jnp.zeros((), x.dtype))
        else:
            raise NotImplementedError(kind)
        # zero out aggregate rows/cols that lie in the padded region
        return _mask_to_logical(out, node.shape)

    def _vec(self, node: MatExpr, ev) -> Array:
        (child,) = node.children
        x = ev(child)
        n, m = child.shape
        v = x[:n, :m].T.reshape(n * m, 1)  # column-major vec
        pshape = padding.padded_shape(node.shape, self.mesh)
        if v.shape[0] != pshape[0]:
            v = jnp.pad(v, ((0, pshape[0] - v.shape[0]), (0, 0)))
        return v

    def _select_index(self, node: MatExpr, ev) -> Array:
        x = ev(node.children[0])
        rows, cols = node.attrs["rows"], node.attrs["cols"]
        pn, pm = x.shape
        keep = jnp.ones((), dtype=bool)
        if rows is not None:
            keep = keep & rows(jnp.arange(pn))[:, None]
        if cols is not None:
            keep = keep & cols(jnp.arange(pm))[None, :]
        return jnp.where(keep, x, jnp.zeros((), x.dtype))

    def _entry_vectors(self, node: MatExpr, ev):
        """Column-major logical-entry vectors (va, vb) of a join_value
        node's operands — the pair matrix's row/col coordinates — plus
        the dtype the DENSE lowering would produce (operand promotion),
        so the streaming result is cast to match it."""
        l, r = node.children
        a, b = ev(l), ev(r)
        va = a[: l.shape[0], : l.shape[1]].T.reshape(-1)
        vb = b[: r.shape[0], : r.shape[1]].T.reshape(-1)
        out_dtype = jnp.result_type(a.dtype, b.dtype)
        return va.astype(jnp.float32), vb.astype(jnp.float32), out_dtype

    def _agg_join_value(self, node: MatExpr, jnode: MatExpr, ev) -> Array:
        """agg(join_on_value(A, B)) without materialising the (na, nb)
        pair matrix: sort-based O((na+nb)·log nb) for structured
        predicate+merge, bounded chunkwise enumeration for black-box
        callables (capped), elementwise for the diagonal."""
        from matrel_tpu.relational import value_join as vj
        kind, axis = node.attrs["agg"], node.attrs["axis"]
        merge_fn = jnode.attrs["merge"]
        pred_fn = jnode.attrs["predicate"]
        pred_kind = jnode.attrs.get("pred_kind")
        merge_kind = jnode.attrs.get("merge_kind")
        na, nb = jnode.shape
        structured = (merge_kind is not None
                      and (pred_kind is not None or pred_fn is None)
                      and kind in vj.AGG_KINDS)
        if (axis != "diag" and not structured
                and na * nb > self.config.join_bruteforce_max_pairs):
            # guard BEFORE evaluating the operands — same guard-first
            # pattern as _join_value; shapes are static
            raise ValueError(
                f"aggregated value-join with callable merge/"
                f"predicate must enumerate {na}x{nb} = {na * nb} "
                f"pairs (> join_bruteforce_max_pairs = "
                f"{self.config.join_bruteforce_max_pairs}). Use "
                f"structured forms (predicate in "
                f"{expr_mod.JOIN_PREDS}, merge in "
                f"{expr_mod.JOIN_MERGES}) for the O(n log n) sort "
                f"path, or raise the cap.")
        va, vb, out_dtype = self._entry_vectors(jnode, ev)
        # a tiny QUERY side isn't worth resharding (GSPMD falls back to
        # full rematerialisation moving small leaf shardings around);
        # the query side is va for row/all aggregates, vb for col
        query_n = na if axis in ("row", "all") else nb
        if (axis != "diag" and self.mesh.size > 1
                and query_n >= 128 * self.mesh.size):
            # BOTH streaming paths are embarrassingly parallel over the
            # query side: the sort path's searchsorted/prefix-gathers
            # and the chunked path's per-row tile reductions each run
            # on query_n/P entries per chip once the query entries are
            # sharded across every device (the other operand
            # replicated — it is read whole by every row's scan)
            from jax.sharding import NamedSharding, PartitionSpec as P
            axes = tuple(self.mesh.axis_names)
            flat = NamedSharding(self.mesh, P(axes))
            repl = NamedSharding(self.mesh, P())
            sa, sb = ((flat, repl) if axis in ("row", "all")
                      else (repl, flat))            # col: roles swap
            va = jax.lax.with_sharding_constraint(va, sa)
            vb = jax.lax.with_sharding_constraint(vb, sb)
        if axis == "diag":
            L = min(na, nb)
            d = merge_fn(va[:L], vb[:L])
            if pred_fn is not None:
                d = jnp.where(pred_fn(va[:L], vb[:L]), d, 0.0)
            out = _diag_reduce(d, kind)
            return self._pad_to_node(
                out.reshape(1, 1).astype(out_dtype), node)
        if structured:
            out = vj.axis_agg_sorted(va, vb, pred_kind or "always",
                                     merge_kind, kind, axis)
        else:
            out = vj.axis_agg_chunked(va, vb, merge_fn, pred_fn, kind,
                                      axis,
                                      self.config.join_chunk_entries)
        if axis == "row":
            out = out.reshape(-1, 1)
        elif axis == "col":
            out = out.reshape(1, -1)
        else:
            out = out.reshape(1, 1)
        return self._pad_to_node(out.astype(out_dtype), node)

    def _join_value(self, node: MatExpr, ev) -> Array:
        """Value-join: all pairs (a_entry, b_entry) with predicate; output is
        the (|A|, |B|) pair matrix (entries merge(va, vb) where predicate
        holds, else 0). Blockwise outer construction. MATERIALISING the
        pair matrix is capped (config.join_pair_cap_entries) — aggregate
        the join for the streaming path (_agg_join_value)."""
        na, nb = node.shape
        cap = self.config.join_pair_cap_entries
        if na * nb > cap:
            raise ValueError(
                f"materialising a {na}x{nb} value-join pair matrix "
                f"({na * nb} entries) exceeds join_pair_cap_entries = "
                f"{cap}. Aggregate the join (e.g. agg(join, 'sum', "
                f"'row')) to stream it without materialisation, or "
                f"raise the cap in MatrelConfig.")
        l, r = node.children
        a, b = ev(l), ev(r)
        va = a[: l.shape[0], : l.shape[1]].T.reshape(-1)  # column-major entries
        vb = b[: r.shape[0], : r.shape[1]].T.reshape(-1)
        merge, pred = node.attrs["merge"], node.attrs["predicate"]
        A = va[:, None]
        B = vb[None, :]
        out = merge(A, B)
        if pred is not None:
            out = jnp.where(pred(A, B), out, jnp.zeros((), out.dtype))
        pshape = padding.padded_shape(node.shape, self.mesh)
        if tuple(out.shape) != pshape:
            out = jnp.pad(out, ((0, pshape[0] - out.shape[0]),
                                (0, pshape[1] - out.shape[1])))
        return out


_HOIST_BYTES = 1 << 20


def _hoist_large_consts(fn, example_args):
    """Turn large trace constants into call-time arguments.

    Sparse leaves embed their payloads (tile stacks, one-hot plan
    tables) as constants of the traced program. XLA treats array
    constants as parameters, but they still ship INSIDE the compile
    request — and the axon relay rejects multi-GB requests (measured
    2026-07-30: a 10M-edge COO plan through compile_expr fails at
    remote_compile; the same op with arrays passed as arguments works).
    Small constants (masks, iotas) stay embedded so XLA can fold them.

    Returns (wrapped_fn, big_consts): call wrapped_fn(*leaves,
    *big_consts). (jax.closure_convert is NOT usable here: it only
    hoists consts that might carry AD perturbations; concrete payload
    arrays stay closed over.)
    """
    from jax.tree_util import tree_unflatten

    import numpy as _np

    def _nbytes(c):
        # consts may be jax Arrays, numpy arrays, or TypedNdArray
        # wrappers (jax 0.9) that expose shape/dtype but not nbytes
        try:
            return int(_np.prod(c.shape)) * _np.dtype(c.dtype).itemsize
        except (AttributeError, TypeError):
            return 0

    closed, out_shape = jax.make_jaxpr(fn, return_shape=True)(
        *example_args)
    consts = closed.consts
    big_ix = [i for i, c in enumerate(consts)
              if _nbytes(c) >= _HOIST_BYTES]
    # keep only the jaxpr and the SMALL consts: holding `closed` (or the
    # full consts list) in the closure would pin the big payload host
    # copies for the plan's lifetime — the very arrays the hoist manages
    jaxpr = closed.jaxpr
    small = {i: c for i, c in enumerate(consts) if i not in set(big_ix)}
    big_vals = [jnp.asarray(consts[i]) for i in big_ix]
    n_leaf = len(example_args)
    n_consts = len(consts)
    out_tree = jax.tree_util.tree_structure(out_shape)
    del closed, consts

    def hoisted(*args):
        leafs, bigs = args[:n_leaf], args[n_leaf:]
        it = iter(bigs)
        cs = [small[i] if i in small else next(it)
              for i in range(n_consts)]
        flat = jax.core.eval_jaxpr(jaxpr, cs, *leafs)
        return tree_unflatten(out_tree, flat)

    # returned even when nothing was hoisted: the trace is already paid
    # for, and handing back the raw fn would make jax.jit trace the
    # whole program a second time on every dense compile
    return hoisted, big_vals


def _example_avals(leaf_order):
    return [jax.ShapeDtypeStruct(l.attrs["matrix"].data.shape,
                                 l.attrs["matrix"].data.dtype)
            for l in leaf_order]


@dataclasses.dataclass
class CompiledPlan:
    """A jitted plan plus its leaf binding order — re-runnable with fresh
    leaf data (the analogue of re-executing an RDD lineage on new blocks).
    ``extra_args`` are hoisted large constants (sparse payloads), appended
    to every call."""

    jitted: Callable
    leaf_order: List[MatExpr]
    optimized: MatExpr
    mesh: Mesh
    config: MatrelConfig
    extra_args: List = dataclasses.field(default_factory=list)
    _donating: Dict[tuple, Callable] = dataclasses.field(default_factory=dict)
    #: compile-time observability record (obs/ event log + explain):
    #: optimize_ms, trace_ms, rewrite-rule hit counts; per-matmul
    #: planner decisions ("matmuls") are added lazily by
    #: :func:`plan_matmul_decisions` so the obs-off compile path does
    #: not pay for them.
    meta: Dict = dataclasses.field(default_factory=dict)

    def run(self, bindings: Optional[Dict[int, BlockMatrix]] = None,
            donate: bool = False) -> BlockMatrix:
        """Execute with current or rebound leaves.

        donate=True hands the REBOUND leaf buffers to XLA (input/output
        aliasing — halves HBM traffic in C←f(C) iteration patterns); the
        donated BlockMatrices must not be used afterwards.
        """
        arrays = []
        donated = []
        for i, l in enumerate(self.leaf_order):
            bound = (bindings or {}).get(l.uid)
            if bound is not None:
                donated.append(i)
            m = bound if bound is not None else l.attrs["matrix"]
            arrays.append(m.data)
        if donate and donated and self.config.donate_intermediates:
            out = self._donating_fn(tuple(donated))(*arrays,
                                                    *self.extra_args)
        else:
            out = self.jitted(*arrays, *self.extra_args)
        return BlockMatrix.from_array(
            out, self.optimized.shape, self.mesh,
            padding.canonical_spec(tuple(out.shape), self.mesh),
            nnz=self.optimized.nnz,
        )

    def bound_runner(self, rebind_uids: tuple = (), donate: bool = False):
        """Low-overhead repeated-execution path for iteration loops (the
        analogue of re-executing a compiled plan across RDD iterations).

        Precomputes the leaf layout ONCE and returns ``fn(*arrays) ->
        jax.Array``: positional raw padded arrays for the leaves named in
        ``rebind_uids`` (in that order), raw padded output — none of
        ``run``'s per-call dict walking, spec derivation or BlockMatrix
        wrapping. With donate=True the rebound buffers are donated
        (C←f(C) patterns run with input/output aliasing).
        """
        uid_pos = {l.uid: i for i, l in enumerate(self.leaf_order)}
        positions = [uid_pos[u] for u in rebind_uids]
        base = [l.attrs["matrix"].data for l in self.leaf_order]
        if donate and positions and self.config.donate_intermediates:
            jfn = self._donating_fn(tuple(sorted(positions)))
        else:
            jfn = self.jitted

        extra = tuple(self.extra_args)
        if not positions:
            return lambda: jfn(*base, *extra)

        def call(*arrays):
            if len(arrays) != len(positions):
                raise ValueError(
                    f"bound runner expects {len(positions)} rebound "
                    f"array(s), got {len(arrays)}")
            argv = list(base)
            for p, a in zip(positions, arrays):
                argv[p] = a
            return jfn(*argv, *extra)

        return call

    def _donating_fn(self, key: tuple):
        """Cached donating variant of the jitted program (key = sorted
        donated argument positions)."""
        jfn = self._donating.get(key)
        if jfn is None:
            jfn = jax.jit(self.jitted.__wrapped__, donate_argnums=key)
            self._donating[key] = jfn
        return jfn

    def hlo(self) -> str:
        """Optimized HLO text — for plan-shape assertions on collectives."""
        arrays = [l.attrs["matrix"].data for l in self.leaf_order]
        return self.jitted.lower(*arrays,
                                 *self.extra_args).compile().as_text()

    def collectives(self) -> Dict[str, int]:
        """Count of each collective op in the compiled HLO — the assertable
        'plan shape' (SURVEY.md §4: the Catalyst comparePlans analogue at
        the physical level)."""
        import re as _re
        text = self.hlo()
        counts: Dict[str, int] = {}
        for op in ("all-gather", "reduce-scatter", "all-reduce",
                   "collective-permute", "all-to-all"):
            n = len(_re.findall(rf"\b{op}\b", text))
            if n:
                counts[op] = n
        return counts

    def explain(self) -> str:
        """Logical/physical plan summary incl. strategies and collectives."""
        from matrel_tpu.ir.expr import pretty
        lines = ["== Optimized plan ==",
                 pretty(self.optimized, mesh=self.mesh,
                        config=self.config)]
        try:
            lines += ["== Collectives ==", str(self.collectives())]
        except Exception:  # matlint: disable=ML007 explain() best-effort — HLO dump can fail on exotic backends; the plan text above still renders
            pass
        return "\n".join(lines)


@dataclasses.dataclass
class MultiPlan:
    """Several optimized roots compiled into ONE XLA program (one fusion
    and CSE domain, one dispatch) — the analogue of a multi-action Spark
    job sharing its lineage. Parity with :class:`CompiledPlan`: rebound
    leaves can be donated (``donate=True``), and the session caches
    compiled MultiPlans in its plan cache alongside single plans
    (``extra_args`` carries the hoisted payloads the byte budget
    accounts)."""

    jitted: Callable
    leaf_order: List[MatExpr]
    optimized: Tuple[MatExpr, ...]
    mesh: Mesh
    config: MatrelConfig
    extra_args: List = dataclasses.field(default_factory=list)
    _donating: Dict[tuple, Callable] = dataclasses.field(
        default_factory=dict)
    meta: Dict = dataclasses.field(default_factory=dict)

    def run(self, bindings: Optional[Dict[int, BlockMatrix]] = None,
            donate: bool = False) -> Tuple[BlockMatrix, ...]:
        """Execute with current or rebound leaves. ``donate=True``
        hands REBOUND leaf buffers to XLA (input/output aliasing —
        the same contract as CompiledPlan.run: donated BlockMatrices
        must not be used afterwards)."""
        arrays = []
        donated = []
        for i, l in enumerate(self.leaf_order):
            bound = (bindings or {}).get(l.uid)
            if bound is not None:
                donated.append(i)
            m = bound if bound is not None else l.attrs["matrix"]
            arrays.append(m.data)
        if donate and donated and self.config.donate_intermediates:
            outs = self._donating_fn(tuple(donated))(*arrays,
                                                     *self.extra_args)
        else:
            outs = self.jitted(*arrays, *self.extra_args)
        return tuple(
            BlockMatrix.from_array(
                out, root.shape, self.mesh,
                padding.canonical_spec(tuple(out.shape), self.mesh),
                nnz=root.nnz)
            for out, root in zip(outs, self.optimized))

    def _donating_fn(self, key: tuple):
        """Cached donating variant (key = sorted donated argument
        positions) — CompiledPlan's idiom."""
        jfn = self._donating.get(key)
        if jfn is None:
            jfn = jax.jit(self.jitted.__wrapped__, donate_argnums=key)
            self._donating[key] = jfn
        return jfn


def _precision_meta(opts, cfg) -> Optional[Dict]:
    """Plan-level precision metadata for ``plan.meta`` (obs events /
    explain): the query SLA, the stamped tier census, and the
    documented worst-case relative error bound over every tiered
    matmul (TIER_EPS · k — the bound bench/soak assert against). None
    under the "default" SLA, so the default compile path pays zero
    extra walks (the bit-identity contract)."""
    if cfg.precision_sla == "default":
        return None
    from matrel_tpu.parallel import planner as planner_mod
    tiers: Dict[str, int] = {}
    bound = [0.0]
    seen: set = set()

    def walk(n: MatExpr):
        if n.uid in seen:
            return
        seen.add(n.uid)
        for c in n.children:
            walk(c)
        t = n.attrs.get("precision_tier")
        if n.kind == "matmul" and t is not None:
            tiers[t] = tiers.get(t, 0) + 1
            eps = planner_mod.TIER_EPS.get(t)
            if eps:
                bound[0] = max(bound[0], eps * n.children[0].shape[1])

    for o in opts:
        walk(o)
    return {"sla": cfg.precision_sla, "tiers": tiers,
            "est_rel_err_bound": bound[0]}


def _fusion_meta(opts, cfg) -> Optional[Dict]:
    """Plan-level fusion roll-up for ``plan.meta`` (obs query events,
    ``history --summary``'s fusion line): region count, merged member
    census, and the modelled dispatch/HBM savings of every stamped
    boundary. None with fusion off — the default compile path pays
    zero extra walks (the bit-identity contract, the _precision_meta
    idiom)."""
    if not cfg.fusion_enable:
        return None
    from matrel_tpu.ir import fusion as fusion_lib
    regions = 0
    census: Dict[str, int] = {}
    saved_d = 0
    saved_b = 0.0
    for o in opts:
        for node in fusion_lib.collect_stamps(o):
            regions += 1
            for k, v in (node.attrs.get("fused_census") or {}).items():
                census[k] = census.get(k, 0) + v
            saved_d += int(node.attrs.get("fused_saved_dispatches") or 0)
            saved_b += float(node.attrs.get("fused_saved_hbm_bytes")
                             or 0.0)
    return {"regions": regions, "census": census,
            "est_saved_dispatches": saved_d,
            "est_saved_hbm_bytes": saved_b}


def _verify_plans(opts, mesh, cfg) -> Optional[List[dict]]:
    """Run the static verifier (matrel_tpu/analysis/) over annotated
    roots when ``config.verify_plans`` asks for it — PRE-execution,
    pre-trace: at "error" an infeasible/misdescribed plan raises here
    and nothing is ever lowered, at "warn" the findings are logged and
    recorded. Returns the diagnostic dicts for plan.meta (None when the
    gate is off, so the obs-off compile path pays nothing). Lazily
    imported to keep the analysis->executor dependency one-way at
    module load."""
    if cfg.verify_plans == "off":
        return None
    from matrel_tpu import analysis
    diags = []
    for o in opts:
        diags.extend(analysis.verify_plan(o, mesh, cfg))
    analysis.enforce(diags, cfg.verify_plans)
    return [d.to_dict() for d in diags]


def compile_exprs(exprs, mesh: Optional[Mesh] = None,
                  config: Optional[MatrelConfig] = None) -> MultiPlan:
    """Compile several expressions into one program with shared leaves."""
    cfg = config or default_config()
    exprs = tuple(exprs)
    all_leaves = []
    seen = set()
    for e in exprs:
        for l in expr_leaves(e):
            if l.uid not in seen:
                seen.add(l.uid)
                all_leaves.append(l)
    if mesh is None:
        mesh = (all_leaves[0].attrs["matrix"].mesh if all_leaves
                else mesh_lib.make_mesh(cfg.mesh_shape, cfg.mesh_axis_names))
    for e in exprs:
        _check_one_mesh(e, mesh)
    grid = mesh_lib.mesh_grid_shape(mesh)
    rule_hits: Dict[str, int] = {}
    # phase(): timed ALWAYS (meta needs the durations on the obs-off
    # path too), emitted as parent-linked spans only when a tracer is
    # active — the pre-span perf_counter pairs, one mechanism
    with trace_lib.phase("plan.optimize", roots=len(exprs)) as sp_opt:
        opts = tuple(planner.annotate_strategies(
            rules.optimize(e, cfg, grid=grid, mesh=mesh,
                           counts=rule_hits),
            mesh, cfg)
            for e in exprs)
        if cfg.fusion_enable:
            # whole-plan fusion boundaries (ir/fusion.py): stamped
            # after strategies/tiers so anchors carry their recipes,
            # before the verifier so MV111 sees every region. Off (the
            # default) this branch constructs nothing — bit-identity.
            from matrel_tpu.ir import fusion as fusion_lib
            opts = tuple(fusion_lib.annotate_fusion(o, mesh, cfg)
                         for o in opts)
    with trace_lib.phase("plan.verify"):
        verify_diags = _verify_plans(opts, mesh, cfg)
    leaf_order = []
    seen = set()
    for o in opts:
        for l in expr_leaves(o):
            if l.uid not in seen:
                seen.add(l.uid)
                leaf_order.append(l)
    low = Lowerer(mesh, cfg)
    if cfg.autotune:
        low.spmv_choice = _autotune_spmv_choices(opts, mesh, cfg)
    fn = low.lower_multi(opts, leaf_order)
    with trace_lib.phase("plan.trace") as sp_tr:
        fn, extra = _hoist_large_consts(fn, _example_avals(leaf_order))
    meta = {"optimize_ms": round(sp_opt.dur_ms, 3),
            "trace_ms": round(sp_tr.dur_ms, 3),
            "rule_hits": rule_hits}
    if verify_diags is not None:
        meta["diagnostics"] = verify_diags
    prec_meta = _precision_meta(opts, cfg)
    if prec_meta is not None:
        meta["precision"] = prec_meta
    fus_meta = _fusion_meta(opts, cfg)
    if fus_meta is not None:
        meta["fusion"] = fus_meta
    return MultiPlan(jitted=jax.jit(fn), leaf_order=leaf_order,
                     optimized=opts, mesh=mesh, config=cfg,
                     extra_args=extra, meta=meta)


# Narrow-operand threshold for the COO SpMV dispatch. The planner's
# layout inference calls _coo_dispatch_plan itself (not this constant)
# so the plan-refusal fallback is honoured too.
COO_NARROW_MAX = 128


#: Matmul operand kinds the SpGEMM dispatch accepts.
_SPGEMM_LEAF_KINDS = ("sparse_leaf", "coo_leaf")


def _spgemm_block_size(node: MatExpr, config=None):
    """The tile edge an S×S matmul's SpGEMM would run at, or None when
    the node is not an S×S candidate at all: both operands must be
    sparse leaves, and two block-sparse operands must already agree on
    block size (their tile grids intersect 1:1). COO operands adopt the
    block-sparse partner's grid, or config.block_size for COO×COO."""
    l, r = node.children
    if (l.kind not in _SPGEMM_LEAF_KINDS
            or r.kind not in _SPGEMM_LEAF_KINDS):
        return None
    sizes = [c.attrs["matrix"].block_size for c in node.children
             if c.kind == "sparse_leaf"]
    if len(sizes) == 2 and sizes[0] != sizes[1]:
        return None
    if sizes:
        return sizes[0]
    cfg = config or default_config()
    return cfg.block_size


def _block_density_of(child: MatExpr, bs: int) -> float:
    """Block-granular density of an S×S operand: block-sparse leaves
    carry it; element-sparse leaves COUNT their touched tiles exactly
    from the host edge lists (memoised per block size). The
    probabilistic lift (ir/stats.block_density) is wrong in both
    directions here: under its uniform-independence assumption any
    element density above ~1/bs² saturates the estimate to ~1.0, so
    CLUSTERED edge lists — the very inputs tile-intersection SpGEMM
    exists for — could never dispatch (review r6), while the exact
    count costs one O(nnz) numpy pass, work from_coo_arrays would
    redo at lowering anyway."""
    import math as _math
    m = child.attrs["matrix"]
    if child.kind == "sparse_leaf":
        return m.density
    memo = getattr(m, "_block_density_memo", None)
    if memo is not None and memo[0] == bs:
        return memo[1]
    import numpy as _np
    gr = _math.ceil(m.shape[0] / bs)
    gc = _math.ceil(m.shape[1] / bs)
    keys = (_np.asarray(m.rows, _np.int64) // bs) * gc \
        + _np.asarray(m.cols, _np.int64) // bs
    d = len(_np.unique(keys)) / max(gr * gc, 1)
    m._block_density_memo = (bs, d)
    return d


def spgemm_out_block_density(node: MatExpr, config=None):
    """Estimated output BLOCK density of an S×S matmul — the quantity
    the dispatch threshold compares. None when not an S×S candidate."""
    from matrel_tpu.ir import stats
    import math as _math
    bs = _spgemm_block_size(node, config)
    if bs is None:
        return None
    l, r = node.children
    kb = max(1, _math.ceil(l.shape[1] / bs))
    return stats.matmul_density(_block_density_of(l, bs),
                                _block_density_of(r, bs), kb)


def _spgemm_dispatch(node: MatExpr, config=None) -> bool:
    """Will this matmul lower through the SpGEMM path? SINGLE source of
    truth, shared by Lowerer._matmul, the planner's strategy pricing
    (choose_strategy_ex), layout inference and matmul_decisions —
    mirroring the _coo_dispatch_plan contract."""
    cfg = config or default_config()
    if cfg.spgemm_density_threshold <= 0.0:
        return False
    est = spgemm_out_block_density(node, cfg)
    return est is not None and est < cfg.spgemm_density_threshold


def spgemm_estimates(node: MatExpr, config=None) -> dict:
    """Observability record for a SpGEMM dispatch (planner.
    matmul_decisions → obs/ query events): estimated output block
    density plus the FLOPs/HBM bytes saved vs the densify fallback."""
    from matrel_tpu.ir import stats
    import math as _math
    cfg = config or default_config()
    bs = _spgemm_block_size(node, cfg)
    l, r = node.children
    k, m = l.shape[1], r.shape[1]
    kb = max(1, _math.ceil(k / bs))

    def nnzb_of(child):
        mtx = child.attrs["matrix"]
        if child.kind == "sparse_leaf":
            return float(mtx.nnzb)
        gr = _math.ceil(child.shape[0] / bs)
        gc = _math.ceil(child.shape[1] / bs)
        return _block_density_of(child, bs) * gr * gc

    rec = stats.spgemm_saved_estimate(nnzb_of(l), nnzb_of(r), kb, k, m,
                                      bs)
    rec["est_out_block_density"] = spgemm_out_block_density(node, cfg)
    rec["block_size"] = bs
    return rec


def spgemm_kernel_choice(node: MatExpr, config=None, mesh=None):
    """(kernel_id, structure_class, source) for a dispatching S×S
    matmul — the SINGLE source of truth shared by the planner's stamp
    (annotate_strategies), the MV110 verifier and the lowering's
    unstamped fallback, mirroring the _spgemm_dispatch contract.
    Structure classification is memoised per operand
    (kernel_registry.structure_of_child, the pair_structure idiom) and
    surfaces in matmul_decisions / explain(analyze=True)."""
    from matrel_tpu.ir import stats
    from matrel_tpu.ops import kernel_registry as kr
    cfg = config or default_config()
    bs = _spgemm_block_size(node, cfg)
    l, r = node.children
    structure = stats.pair_structure_class(
        kr.structure_of_child(l, bs), kr.structure_of_child(r, bs))
    est = spgemm_estimates(node, cfg)
    npairs = max(int(round(est.get("est_pairs") or 0.0)), 1)
    side = max(l.shape[0], l.shape[1], r.shape[1])
    kid, source = kr.select_kernel(structure, bs, npairs, cfg,
                                   side=side, mesh=mesh)
    return kid, structure, source


def _coo_dispatch_plan(node: MatExpr):
    """The EdgeSpMVPlan a coo_leaf matmul node will dispatch through
    _coo_spmv_stack, or None (the densify path). SINGLE source of truth
    for the narrow-operand dispatch, shared by Lowerer._matmul and the
    autotune walk so the two can never drift."""
    l, r = node.children
    if l.kind == "coo_leaf":
        k = r.shape[1]
        return (l.attrs["matrix"]._get_plan()
                if 0 < k <= COO_NARROW_MAX else None)
    if r.kind == "coo_leaf":
        k = l.shape[0]
        return (r.attrs["matrix"]._get_plan_t()
                if 0 < k <= COO_NARROW_MAX else None)
    return None


def _autotune_spmv_choices(opts, mesh, cfg) -> dict:
    """Measured SpMV executor variants for every COO matmul this plan
    will dispatch through _coo_spmv_stack (config.autotune on): maps
    id(plan) -> (plan, "compact"/"expanded"). Runs OUTSIDE tracing, at
    compile time — measurement launches its own jitted probes. Dispatch
    conditions come from _coo_dispatch_plan (shared with _matmul);
    anything else keeps the hand defaults."""
    from matrel_tpu.parallel import autotune

    choices: dict = {}
    seen: set = set()

    def visit(n: MatExpr):
        if n.uid in seen:        # expressions are DAGs — walk each
            return               # shared node once
        seen.add(n.uid)
        if n.kind == "matmul" and any(c.kind == "coo_leaf"
                                      for c in n.children):
            plan = _coo_dispatch_plan(n)
            if plan is not None and id(plan) not in choices:
                best = autotune.lookup_or_measure_spmv(plan, mesh, cfg)
                if best is not None:
                    choices[id(plan)] = (plan, best)
        for c in n.children:
            visit(c)

    for o in opts:
        visit(o)
    return choices


def _check_one_mesh(expr: MatExpr, mesh: Mesh) -> None:
    """All leaves (dense and sparse) must live on the plan's mesh — mixed
    meshes would silently produce cross-device copies or wrong shardings."""
    def walk(n: MatExpr):
        if n.kind in ("leaf", "sparse_leaf"):
            m = n.attrs["matrix"].mesh
            if m is not mesh and tuple(m.devices.ravel()) != tuple(
                    mesh.devices.ravel()):
                raise ValueError(
                    "expression mixes matrices from different meshes: "
                    f"{dict(m.shape)} vs plan mesh {dict(mesh.shape)}")
        for c in n.children:
            walk(c)
    walk(expr)


def compile_expr(expr: MatExpr, mesh: Optional[Mesh] = None,
                 config: Optional[MatrelConfig] = None) -> CompiledPlan:
    """optimize → plan → lower → jit. The full Catalyst pipeline analogue."""
    cfg = config or default_config()
    lvs = expr_leaves(expr)
    if mesh is None:
        mesh = lvs[0].attrs["matrix"].mesh if lvs else mesh_lib.make_mesh(
            cfg.mesh_shape, cfg.mesh_axis_names)
    _check_one_mesh(expr, mesh)
    rule_hits: Dict[str, int] = {}
    # phase spans: same mechanism (and meta fields) as compile_exprs
    with trace_lib.phase("plan.optimize") as sp_opt:
        opt = rules.optimize(expr, cfg,
                             grid=mesh_lib.mesh_grid_shape(mesh),
                             mesh=mesh, counts=rule_hits)
        opt = planner.annotate_strategies(opt, mesh, cfg)
        if cfg.fusion_enable:
            # fusion boundaries after strategies, before the verifier
            # (the compile_exprs ordering — one contract)
            from matrel_tpu.ir import fusion as fusion_lib
            opt = fusion_lib.annotate_fusion(opt, mesh, cfg)
    with trace_lib.phase("plan.verify"):
        verify_diags = _verify_plans((opt,), mesh, cfg)
    leaf_order = expr_leaves(opt)
    low = Lowerer(mesh, cfg)
    if cfg.autotune:
        low.spmv_choice = _autotune_spmv_choices((opt,), mesh, cfg)
    fn = low.lower(opt, leaf_order)
    with trace_lib.phase("plan.trace") as sp_tr:
        fn, extra = _hoist_large_consts(fn, _example_avals(leaf_order))
    jitted = jax.jit(fn)
    meta = {"optimize_ms": round(sp_opt.dur_ms, 3),
            "trace_ms": round(sp_tr.dur_ms, 3),
            "rule_hits": rule_hits}
    if verify_diags is not None:
        meta["diagnostics"] = verify_diags
    prec_meta = _precision_meta((opt,), cfg)
    if prec_meta is not None:
        meta["precision"] = prec_meta
    fus_meta = _fusion_meta((opt,), cfg)
    if fus_meta is not None:
        meta["fusion"] = fus_meta
    return CompiledPlan(jitted=jitted, leaf_order=leaf_order, optimized=opt,
                        mesh=mesh, config=cfg, extra_args=extra, meta=meta)


def plan_matmul_decisions(plan) -> List[dict]:
    """Per-matmul planner-decision records for a compiled plan (obs/
    event log, ``explain(analyze=True)``), computed on FIRST access and
    cached in ``plan.meta`` — deriving them re-walks the tree through
    ``infer_layout``/``comm_cost``, work the obs-off compile path must
    not pay for."""
    meta = plan.meta
    if meta is None:
        return []
    if "matmuls" not in meta:
        roots = (plan.optimized if isinstance(plan.optimized, tuple)
                 else (plan.optimized,))
        meta["matmuls"] = [
            d for o in roots
            for d in planner.matmul_decisions(o, plan.mesh, plan.config)]
        ivm = meta.get("ivm")
        if isinstance(ivm, dict):
            # delta-patch plans (serve/ivm.py; docs/IVM.md): the
            # optimizer may rebuild the stamped root, so the pricing
            # provenance rides plan.meta and is threaded onto the
            # decision records here (planner.matmul_decisions also
            # reads a surviving root stamp — one meaning, two feeds)
            for d in meta["matmuls"]:
                d.setdefault("delta_rule", ivm.get("rule"))
                d.setdefault("delta_est_saved_flops",
                             ivm.get("est_saved_flops"))
    return meta["matmuls"]


def multiplan_root_decisions(plan: MultiPlan) -> List[List[dict]]:
    """Per-ROOT planner-decision records for a MultiPlan, aligned with
    ``plan.optimized`` — the per-root obs feed (session.run_many emits
    one query event per root, each carrying its OWN matmuls instead of
    the batch aggregate). Lazily derived and cached in ``plan.meta``
    like :func:`plan_matmul_decisions`, so the obs-off batch path pays
    nothing."""
    meta = plan.meta
    if meta is None:
        return [[] for _ in plan.optimized]
    if "matmuls_per_root" not in meta:
        meta["matmuls_per_root"] = [
            planner.matmul_decisions(o, plan.mesh, plan.config)
            for o in plan.optimized]
    return meta["matmuls_per_root"]


#: Decision-record columns the provenance ledger keeps (obs tier 4):
#: the chosen strategy, WHY (autotune/model/override), and the
#: precision tier — the coefficient provenance a lineage audit needs,
#: without the per-matmul byte/FLOP estimates the query event carries.
_PROVENANCE_KEEP = ("strategy", "source", "precision_tier",
                    "delta_rule")


def plan_provenance(plan, decisions: Optional[List[dict]] = None
                    ) -> List[dict]:
    """A compiled plan's strategy/tier/coefficient provenance,
    projected for the answer ledger (obs/provenance.py). ``decisions``
    lets MultiPlan callers pass ONE root's records
    (``multiplan_root_decisions``) instead of the batch aggregate.
    Same lazy-derivation contract as :func:`plan_matmul_decisions`:
    the ledger-off path never calls this."""
    if decisions is None:
        decisions = plan_matmul_decisions(plan)
    return [{k: d[k] for k in _PROVENANCE_KEEP
             if d.get(k) is not None} for d in decisions]


def execute(expr: MatExpr, mesh: Optional[Mesh] = None,
            config: Optional[MatrelConfig] = None) -> BlockMatrix:
    return compile_expr(expr, mesh, config).run()


# ---------------------------------------------------------------------------
# Unit-program emission — the region seam (ir/fusion.py; docs/FUSION.md)
#
# The default executor compiles the WHOLE plan into one program; these
# builders are the measurable decomposition of that spectrum's other
# end: ``compile_staged_units`` emits one jitted program PER PHYSICAL
# OP (the per-op dispatch floor — a dispatch and an HBM round-trip per
# plan edge), ``compile_region_units`` one program PER FUSED REGION
# (XLA sees the whole segment). ``bench.py --fusion`` sweeps the two;
# the autotune ``fuse|`` loop measures a single region's pair through
# the same machinery. This module is the ONE sanctioned jit seam —
# matlint ML010 keeps jitted-program emission here (and utils/).
# ---------------------------------------------------------------------------

#: Leaf kinds whose payloads stay INSIDE a unit as trace constants
#: (their lowerings read static host metadata off the node attrs).
_UNIT_CONST_LEAVES = ("sparse_leaf", "coo_leaf")


def _unit_fn(low: Lowerer, root: MatExpr,
             input_uids: Tuple[int, ...]):
    """One jitted program computing ``root`` from its unit inputs
    (everything not in ``input_uids`` — members of the unit's region,
    sparse-payload leaves — lowers inside). Members lower through the
    Lowerer's per-node paths, byte-for-byte the staged lowerings, so
    fused and staged units agree exactly."""

    def fn(*arrs):
        env = dict(zip(input_uids, arrs))

        def lev(n: MatExpr):
            v = env.get(n.uid)
            if v is not None:
                return v
            v = low._eval(n, lev, (), {})  # unit-program member — jitted as one region by the seam builders below
            env[n.uid] = v
            return v

        return lev(root)

    return jax.jit(fn)


@dataclasses.dataclass
class UnitPrograms:
    """An expression compiled as a SEQUENCE of jitted unit programs —
    ``dispatches`` programs per run (the quantity fusion shrinks).
    ``run()`` executes the units in topo order over raw padded arrays
    and returns the root unit's output."""

    #: (node, jitted fn, input uids, member count) in execution order.
    units: List
    optimized: MatExpr
    leaf_order: List[MatExpr]
    mesh: Mesh
    config: MatrelConfig

    @property
    def dispatches(self) -> int:
        return len(self.units)

    def run(self, bindings: Optional[Dict[int, Array]] = None):
        env = {l.uid: l.attrs["matrix"].data for l in self.leaf_order}
        if bindings:
            env.update(bindings)
        for node, fn, input_uids, _n in self.units:
            env[node.uid] = fn(*(env[u] for u in input_uids))
        return env[self.optimized.uid]


def _build_units(opt: MatExpr, mesh: Mesh, cfg: MatrelConfig,
                 per_region: bool) -> UnitPrograms:
    from matrel_tpu.ir import fusion as fusion_lib
    low = Lowerer(mesh, cfg)
    units: List = []
    leaf_order: List[MatExpr] = []
    seen: set = set()
    member_of: Dict[int, int] = {}     # member uid -> region root uid
    if per_region:
        for stamp in fusion_lib.collect_stamps(opt):
            for u in stamp.attrs.get("fused_members") or ():
                member_of[u] = stamp.uid

    def walk(n: MatExpr):
        if n.uid in seen:
            return
        seen.add(n.uid)
        for c in n.children:
            walk(c)
        if n.kind == "leaf":
            leaf_order.append(n)
            return
        if n.kind in _UNIT_CONST_LEAVES:
            return                      # consts inside the consumer unit
        if n.uid in member_of:
            return                      # lowers inside its region unit
        if per_region and "fused_region" in n.attrs:
            members = fusion_lib.region_nodes(n)
            inputs = []
            in_seen = set()
            for m in members.values():
                for c in m.children:
                    if (c.uid not in members
                            and c.kind not in _UNIT_CONST_LEAVES
                            and c.uid not in in_seen):
                        in_seen.add(c.uid)
                        inputs.append(c.uid)
            units.append((n, _unit_fn(low, n, tuple(inputs)),
                          tuple(inputs), len(members)))
            return
        inputs = tuple(c.uid for c in n.children
                       if c.kind not in _UNIT_CONST_LEAVES)
        units.append((n, _unit_fn(low, n, inputs), inputs, 1))

    walk(opt)
    if not units:                       # a bare leaf plan: identity unit
        units.append((opt, jax.jit(lambda x: x), (opt.uid,), 1))
    return UnitPrograms(units=units, optimized=opt,
                        leaf_order=leaf_order, mesh=mesh, config=cfg)


def compile_staged_units(expr: MatExpr, mesh: Optional[Mesh] = None,
                         config: Optional[MatrelConfig] = None
                         ) -> UnitPrograms:
    """One jitted program PER PHYSICAL OP — the staged dispatch floor
    the fused form is measured against (fusion stamps, if any, are
    ignored: every plan edge pays its dispatch and HBM round-trip)."""
    cfg = config or default_config()
    lvs = expr_leaves(expr)
    if mesh is None:
        mesh = lvs[0].attrs["matrix"].mesh if lvs else mesh_lib.make_mesh(
            cfg.mesh_shape, cfg.mesh_axis_names)
    opt = planner.annotate_strategies(
        rules.optimize(expr, cfg, grid=mesh_lib.mesh_grid_shape(mesh),
                       mesh=mesh), mesh, cfg)
    return _build_units(opt, mesh, cfg, per_region=False)


def compile_region_units(expr: MatExpr, mesh: Optional[Mesh] = None,
                         config: Optional[MatrelConfig] = None
                         ) -> UnitPrograms:
    """One jitted program PER FUSED REGION (non-region nodes keep one
    each) — requires ``config.fusion_enable``; the region grammar is
    ``ir/fusion.annotate_fusion``'s, so the emitted boundaries are
    exactly the ones MV111 verifies and the bench sweeps."""
    cfg = config or default_config()
    lvs = expr_leaves(expr)
    if mesh is None:
        mesh = lvs[0].attrs["matrix"].mesh if lvs else mesh_lib.make_mesh(
            cfg.mesh_shape, cfg.mesh_axis_names)
    opt = planner.annotate_strategies(
        rules.optimize(expr, cfg, grid=mesh_lib.mesh_grid_shape(mesh),
                       mesh=mesh), mesh, cfg)
    if cfg.fusion_enable:
        from matrel_tpu.ir import fusion as fusion_lib
        opt = fusion_lib.annotate_fusion(opt, mesh, cfg)
    return _build_units(opt, mesh, cfg, per_region=True)


def region_probe_programs(root_node: MatExpr, member_uids,
                          mesh: Mesh, cfg: MatrelConfig):
    """(fused_fn, staged_units, input_uids, probe_arrays, root_uid)
    for ONE region — the autotune ``fuse|`` measurement harness
    (lookup_or_measure_fusion). Region inputs are replaced by
    synthetic padded f32 probes; regions whose members read
    sparse-leaf payloads return None (the probe cannot substitute
    static tile metadata — the model decides there)."""
    import numpy as _np
    members = {root_node.uid: root_node}
    want = set(member_uids)
    stack = [root_node]
    while stack:
        n = stack.pop()
        for c in n.children:
            if c.uid in want and c.uid not in members:
                members[c.uid] = c
                stack.append(c)
    inputs: List[MatExpr] = []
    in_seen: set = set()
    for m in members.values():
        for c in m.children:
            if c.uid in members or c.uid in in_seen:
                continue
            if c.kind in _UNIT_CONST_LEAVES:
                return None
            in_seen.add(c.uid)
            inputs.append(c)
    low = Lowerer(mesh, cfg)
    input_uids = tuple(c.uid for c in inputs)
    fused = _unit_fn(low, root_node, input_uids)
    staged: List = []
    order: List[MatExpr] = []
    seen: set = set()

    def topo(n: MatExpr):
        if n.uid in seen or n.uid not in members:
            return
        seen.add(n.uid)
        for c in n.children:
            topo(c)
        order.append(n)

    topo(root_node)
    for n in order:
        ins = tuple(c.uid for c in n.children)
        staged.append((n, _unit_fn(low, n, ins), ins))
    rng = _np.random.default_rng(0)
    arrays = {c.uid: jnp.asarray(rng.standard_normal(
        padding.padded_shape(c.shape, mesh)).astype(_np.float32))
        for c in inputs}
    return fused, staged, input_uids, arrays, root_node.uid
