"""Matrix IO — the ingestion layer (the reference loads matrices from
HDFS text/CSV/MatrixMarket into block RDDs; SURVEY.md §2 "Block
representation").

Formats:
  - .npy            dense, single file (numpy)
  - .mtx            MatrixMarket via scipy → BlockSparseMatrix
  - .csv            "i,j,value" coordinate triples → dense or block-sparse
  - tiled directory a directory of `tile_R_C.npy` files + meta.json —
                    the multi-file layout for matrices produced shard-wise
                    (written/read with a thread pool; the Spark-side
                    analogue of one part-file per partition)
"""

from __future__ import annotations

import json
import math
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Optional, Tuple

import numpy as np

from matrel_tpu.config import MatrelConfig
from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.core.sparse import BlockSparseMatrix
from matrel_tpu.utils import native


def load_npy(path: str, mesh=None, config: Optional[MatrelConfig] = None
             ) -> BlockMatrix:
    return BlockMatrix.from_numpy(np.load(path), mesh=mesh, config=config)


def save_npy(path: str, m: BlockMatrix) -> None:
    np.save(path, m.to_numpy())


def load_mtx(path: str, mesh=None, block_size: Optional[int] = None,
             config: Optional[MatrelConfig] = None) -> BlockSparseMatrix:
    """MatrixMarket coordinate file → block-sparse.

    Parses with the native C++ reader (native/mtx_reader.cc) when built;
    falls back to scipy for formats it declines (complex field)."""
    parsed = native.mtx_read(path)
    if parsed is not None:
        shape, rows, cols, vals = parsed
        import scipy.sparse as sps
        # Keep float64 here; from_scipy casts to the configured dtype, so
        # native and scipy-fallback paths yield identical matrices.
        sp = sps.coo_matrix((vals, (rows, cols)), shape=shape)
        return BlockSparseMatrix.from_scipy(sp, block_size=block_size,
                                            mesh=mesh, config=config)
    import scipy.io
    sp = scipy.io.mmread(path)
    return BlockSparseMatrix.from_scipy(sp.tocoo(), block_size=block_size,
                                        mesh=mesh, config=config)


def load_mtx_coo(path: str):
    """MatrixMarket coordinate file → element-sparse ``COOMatrix``.

    The right loader for graph-shaped sparsity (densities that touch
    every 512² tile — block-sparse densification would explode); the
    matrix compiles into the one-hot MXU SpMV plan on first matvec.
    Native C++ parse when built, scipy fallback otherwise."""
    from matrel_tpu.core.coo import COOMatrix

    parsed = native.mtx_read(path)
    if parsed is not None:
        shape, rows, cols, vals = parsed
        return COOMatrix.from_edges(rows, cols, vals.astype(np.float32),
                                    shape=shape)
    import scipy.io
    return COOMatrix.from_scipy(scipy.io.mmread(path))


def read_edges_csv(path: str):
    """Raw 'i,j[,value]' triples → (rows, cols, vals) host arrays; the
    value column defaults to 1.0. Native C parser when built, numpy
    fallback otherwise. Shared by ``load_coo_csv`` and the CLI."""
    parsed = native.coo_csv_read(path)
    if parsed is not None:
        rows, cols, v64 = parsed
        return rows, cols, v64.astype(np.float32)
    data = np.loadtxt(path, delimiter=",", ndmin=2)
    rows = data[:, 0].astype(np.int64)
    cols = data[:, 1].astype(np.int64)
    vals = (data[:, 2].astype(np.float32) if data.shape[1] > 2
            else np.ones(len(rows), np.float32))
    return rows, cols, vals


def load_coo_csv(path: str, shape: Tuple[int, int], mesh=None,
                 block_size: Optional[int] = None, dense: bool = False,
                 config: Optional[MatrelConfig] = None):
    """'i,j,value' triples (the reference's text ingestion format)."""
    rows, cols, vals = read_edges_csv(path)
    if dense:
        out = np.zeros(shape, dtype=np.float32)
        np.add.at(out, (rows, cols), vals)
        return BlockMatrix.from_numpy(out, mesh=mesh, config=config,
                                      nnz=len(vals))
    import scipy.sparse as sps
    sp = sps.coo_matrix((vals, (rows, cols)), shape=shape)
    return BlockSparseMatrix.from_scipy(sp, block_size=block_size, mesh=mesh,
                                        config=config)


# -- tiled directory format -------------------------------------------------


def save_tiled(directory: str, m: BlockMatrix, tile: int = 4096,
               workers: int = 8) -> None:
    """Write a matrix as tile_R_C.npy part-files + meta.json."""
    os.makedirs(directory, exist_ok=True)
    host = m.to_numpy()
    n, mm = host.shape
    gr, gc = math.ceil(n / tile), math.ceil(mm / tile)

    def write(rc):
        r, c = rc
        part = host[r * tile:(r + 1) * tile, c * tile:(c + 1) * tile]
        np.save(os.path.join(directory, f"tile_{r}_{c}.npy"), part)

    with ThreadPoolExecutor(max_workers=workers) as ex:
        list(ex.map(write, [(r, c) for r in range(gr) for c in range(gc)]))
    with open(os.path.join(directory, "meta.json"), "w") as f:
        json.dump({"shape": [n, mm], "tile": tile, "grid": [gr, gc],
                   "dtype": str(host.dtype)}, f)


def load_tiled(directory: str, mesh=None,
               config: Optional[MatrelConfig] = None,
               workers: int = 8) -> BlockMatrix:
    with open(os.path.join(directory, "meta.json")) as f:
        meta = json.load(f)
    n, mm = meta["shape"]
    tile = meta["tile"]
    gr, gc = meta["grid"]
    out = np.zeros((n, mm), dtype=meta.get("dtype", "float32"))

    def read(rc):
        r, c = rc
        part = np.load(os.path.join(directory, f"tile_{r}_{c}.npy"))
        out[r * tile:r * tile + part.shape[0],
            c * tile:c * tile + part.shape[1]] = part

    with ThreadPoolExecutor(max_workers=workers) as ex:
        list(ex.map(read, [(r, c) for r in range(gr) for c in range(gc)]))
    return BlockMatrix.from_numpy(out, mesh=mesh, config=config)
