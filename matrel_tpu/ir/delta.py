"""Incremental view maintenance — the delta algebra (docs/IVM.md).

The result cache (serve/result_cache.py) treats a catalog rebind as a
transitive kill: correct, but production dashboards re-run the same
queries over *slightly changed* matrices (new edges in a graph,
appended rows in a feature matrix), and a kill makes every repeat pay
full recompute. This module is the algebra that lets the cache PATCH
instead: given a cached entry ``R = f(A, ...)`` and a small update
``A' = A + ΔA``, derive a patch expression computing ``f(A', ...)``
from ``R`` and ΔA — the MatFast amortization thesis (PAPER.md [P2])
pushed one level up, and the R8 rank-1 push-through generalized from
rank 1 to rank k and from one rewrite site to the whole expression
grammar.

Delta representations (:class:`MatrixDelta`):
  coo      edge-style updates (rows, cols, vals) — a stream append /
           expiry batch. Canonically FACTORED: a c-edge COO delta is
           exactly the rank-c update ``ΔA = U·Vᵀ`` with one scaled
           one-hot column per edge, so every product against ΔA is a
           thin dense product (the R8 family at rank c), and the
           factor leaves are REBINDABLE — steady-state streams re-run
           one compiled patch plan per entry with fresh factor data
           instead of recompiling (CompiledPlan.run(bindings=...)).
  lowrank  an explicit (U, V) pair, ``ΔA = U·Vᵀ`` — appended feature
           panels, rank-k model corrections.
  dense    a same-shaped correction matrix — the fallback form, also
           the materialization every other kind lowers to for
           elementwise contexts.

Sparse ΔA·B: when the delta's sparse form multiplies a sparse leaf,
the emitted product is an S×S matmul over two sparse leaves — exactly
what ``executor._spgemm_dispatch`` routes through the PR 10 kernel
registry (power-law edge deltas are its home class). The derivation
consults the dispatch predicate so the patch is PRICED the way it will
actually lower.

Rule table (Δf for one changed operand A; ``None`` = structural zero):
  leaf(A)                 ΔA
  transpose(x)            Δxᵀ
  matmul(a,b)  a only     Δa·b        (thin: U·(Vᵀ·b) when factored)
               b only     a·Δb
               both       Δa·b_old + a_new·Δb   (exact; the Gram /
                          linreg rank-k correction: Δ(XᵀX) =
                          ΔXᵀ·X + X'ᵀ·ΔX)
  elemwise add/sub        Δa ± Δb
  elemwise mul            Δa∘b_old + a_new∘Δb   (exact)
  elemwise div            Δa / b      (b must be independent)
  scalar mul/add          s·Δa / Δa
  agg sum|avg (any axis)  agg(Δa)
  vec                     vec(Δa)
  rank1(base,u,v)         Δbase       (u, v must be independent)
  refine hook             root attr ``delta_refine`` — an iterative
                          re-solve from the cached value (PageRank
                          warm restart; :func:`pagerank_warm_restart`)
  everything else         ineligible (select_*, joins, min/max/count,
                          pow, solve, inverse) → the caller falls back
                          to today's transitive kill, so correctness
                          never regresses.

Subtree reuse: the derivation threads a ``known`` map of structurally
matching cached entries (keyed by :func:`core_key`, which normalizes
the changed operand's identity) so the delta of an interior entry
patched earlier in the same generation enters downstream patches as a
LEAF instead of a recomputation — delta propagation through the cached
DAG, not per-entry re-derivation.

Nothing here runs on the default path: ``register_delta`` unused means
no MatrixDelta is ever constructed (``_CONSTRUCTED`` is the
poisoned-init test hook, the fusion ``_CONSTRUCTED`` idiom).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from matrel_tpu.config import MatrelConfig, default_config
from matrel_tpu.ir import expr as E
from matrel_tpu.ir.expr import MatExpr

#: Primary-rule vocabulary a patch stamp may carry (MV113 checks
#: membership; the autotune ``ivm|`` key embeds it).
DELTA_RULES = ("linear", "rank_k", "rank_k_both", "spgemm", "refine")

#: f32/HIGHEST per-product relative error unit — the MV108 bound table's
#: "f32" row (planner.TIER_EPS); patches compound it per generation.
_F32_EPS = 2.0 ** -20

#: Construction counter — the bit-identity test hook (ir/fusion.py's
#: ``_CONSTRUCTED`` idiom): the default path must never build a delta.
_CONSTRUCTED = {"count": 0}


class DeltaIneligible(Exception):
    """Internal control flow: the expression has no derivable patch."""


# ---------------------------------------------------------------------------
# MatrixDelta — the update payload, in whichever form the caller has it
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class MatrixDelta:
    """One registered update ``ΔA`` for a bound catalog matrix.

    kind: "coo" | "lowrank" | "dense" (see module docstring).
    shape: ΔA's logical shape (== the bound matrix's).
    integral: every delta entry is an exact integer — graph-count
      patches then ride the int paths EXACTLY (err bound 0).
    """

    kind: str
    shape: Tuple[int, int]
    rows: Optional[np.ndarray] = None
    cols: Optional[np.ndarray] = None
    vals: Optional[np.ndarray] = None
    u: Optional[np.ndarray] = None        # (n, c)
    v: Optional[np.ndarray] = None        # (m, c)
    dense: Optional[np.ndarray] = None    # (n, m)
    integral: bool = False
    _factors: Optional[tuple] = dataclasses.field(default=None,
                                                  repr=False)
    _dense_bm: Optional[object] = dataclasses.field(default=None,
                                                    repr=False)
    _sparse_bm: Optional[object] = dataclasses.field(default=None,
                                                     repr=False)

    def __post_init__(self):
        _CONSTRUCTED["count"] += 1

    # -- forms --------------------------------------------------------------

    @property
    def rank(self) -> Optional[int]:
        """Factored rank: COO nnz (one rank-1 term per edge), lowrank
        column count; None for dense (no cheap factorisation)."""
        if self.kind == "coo":
            return int(self.rows.shape[0])
        if self.kind == "lowrank":
            return int(self.u.shape[1])
        return None

    @property
    def nnz(self) -> Optional[int]:
        if self.kind == "coo":
            return int(self.rows.shape[0])
        if self.kind == "dense":
            return int(np.count_nonzero(self.dense))
        return None

    def to_dense_numpy(self) -> np.ndarray:
        """ΔA as a host array (the shared lowering of every kind)."""
        if self.kind == "dense":
            return np.asarray(self.dense, np.float32)
        if self.kind == "lowrank":
            return (np.asarray(self.u, np.float32)
                    @ np.asarray(self.v, np.float32).T)
        out = np.zeros(self.shape, np.float32)
        np.add.at(out, (self.rows, self.cols),
                  np.asarray(self.vals, np.float32))
        return out

    def factors(self, mesh, config: Optional[MatrelConfig] = None):
        """(U, V) dense BlockMatrices with ``ΔA = U·Vᵀ`` — the
        rebindable thin form — or None when the delta has no cheap
        factorisation (dense kind, or rank above
        ``config.delta_rank_max``: a fat factored product would cost
        more than it saves)."""
        cfg = config or default_config()
        r = self.rank
        if r is None or r > cfg.delta_rank_max:
            return None
        if self._factors is None:
            from matrel_tpu.core.blockmatrix import BlockMatrix
            if self.kind == "lowrank":
                un = np.asarray(self.u, np.float32)
                vn = np.asarray(self.v, np.float32)
            else:
                # one scaled one-hot column per edge: U[:, t] =
                # vals[t]·e_rows[t], V[:, t] = e_cols[t]
                c = max(r, 1)
                un = np.zeros((self.shape[0], c), np.float32)
                vn = np.zeros((self.shape[1], c), np.float32)
                if r:
                    t = np.arange(r)
                    un[self.rows, t] = np.asarray(self.vals, np.float32)
                    vn[self.cols, t] = 1.0
            self._factors = (
                BlockMatrix.from_numpy(un, mesh=mesh, config=cfg,
                                       integral=self.integral),
                BlockMatrix.from_numpy(vn, mesh=mesh, config=cfg,
                                       integral=self.integral))
        return self._factors

    def materialize(self, mesh, config: Optional[MatrelConfig] = None):
        """ΔA as a dense BlockMatrix (elementwise contexts; rebindable
        under the ``delta_dense`` role). Cached per delta."""
        if self._dense_bm is None:
            from matrel_tpu.core.blockmatrix import BlockMatrix
            cfg = config or default_config()
            self._dense_bm = BlockMatrix.from_numpy(
                self.to_dense_numpy(), mesh=mesh, config=cfg,
                integral=self.integral)
        return self._dense_bm

    def sparse(self, mesh, block_size: int,
               config: Optional[MatrelConfig] = None):
        """ΔA as a BlockSparseMatrix leaf payload — the S×S form whose
        products against sparse leaves dispatch the tile-intersection
        SpGEMM (ops/spgemm.py via executor._spgemm_dispatch). None for
        lowrank (no coordinate list to bucket)."""
        if self.kind == "lowrank":
            return None
        if self._sparse_bm is None or \
                self._sparse_bm.block_size != block_size:
            from matrel_tpu.core.sparse import BlockSparseMatrix
            cfg = config or default_config()
            if self.kind == "coo":
                self._sparse_bm = BlockSparseMatrix.from_coo_arrays(
                    self.rows, self.cols, self.vals, self.shape,
                    block_size=block_size, mesh=mesh, config=cfg)
            else:
                self._sparse_bm = BlockSparseMatrix.from_numpy(
                    self.to_dense_numpy(), block_size=block_size,
                    mesh=mesh, config=cfg)
        return self._sparse_bm

    def apply_to(self, old, mesh, config: Optional[MatrelConfig] = None):
        """The rebound value ``A' = A + ΔA`` in the OLD binding's
        representation (dense BlockMatrix stays dense — one scatter-add
        on device; BlockSparseMatrix rebuilds its touched tiles on
        host). Integral/int_abs_max metadata composes conservatively so
        the precision planner's int-exactness proof stays honest."""
        import jax
        from jax.sharding import NamedSharding
        from matrel_tpu.core.blockmatrix import BlockMatrix
        from matrel_tpu.core.sparse import BlockSparseMatrix
        cfg = config or default_config()
        if isinstance(old, BlockSparseMatrix):
            arr = old.to_numpy()
            arr = arr + self.to_dense_numpy().astype(arr.dtype)
            return BlockSparseMatrix.from_numpy(
                arr, block_size=old.block_size, mesh=mesh, config=cfg,
                dtype=old.dtype)
        if not isinstance(old, BlockMatrix):
            raise TypeError(
                f"register_delta target must be a BlockMatrix or "
                f"BlockSparseMatrix, got {type(old).__name__}")
        if self.kind == "coo":
            data = old.data.at[self.rows, self.cols].add(
                np.asarray(self.vals, old.data.dtype))
        else:
            pad = np.zeros(old.padded_shape, np.float32)
            d = self.to_dense_numpy()
            pad[: self.shape[0], : self.shape[1]] = d
            data = old.data + jax.device_put(  # matlint: disable=ML008 delta ingestion — a freshly-built host correction placed AT the operand's existing layout (no layout change to price)
                pad.astype(old.data.dtype),
                NamedSharding(mesh, old.spec))
        integral = bool(old.integral and self.integral)
        amax = None
        if integral and old.int_abs_max is not None:
            try:
                amax = float(old.int_abs_max) + float(
                    np.abs(self.to_dense_numpy()).max()
                    if self.kind != "coo"
                    else (np.abs(self.vals).max() if self.rank else 0.0))
            except ValueError:
                amax = None
        return dataclasses.replace(
            old, data=data, nnz=None, integral=integral,
            int_abs_max=amax)

    def signature(self) -> tuple:
        """Patch-plan reuse key: two deltas with equal signatures
        produce structurally identical patch plans, so the plane can
        rebind factor/dense leaves instead of recompiling (constant
        edge-batch streams hit this every step)."""
        return (self.kind, self.shape, self.rank, self.integral)


def as_delta(payload, old, kind: str = "auto",
             config: Optional[MatrelConfig] = None) -> MatrixDelta:
    """Lift whatever the caller has into a :class:`MatrixDelta`.

    Accepted payloads: a COOMatrix; ``(rows, cols[, vals])`` index
    arrays (kind "coo"); ``(U, V)`` with ``ΔA = U·Vᵀ`` (kind
    "lowrank"); a same-shaped ndarray/BlockMatrix (kind "dense").
    ``kind="auto"`` disambiguates by shape; pass it explicitly when a
    2-tuple could mean either."""
    from matrel_tpu.core.blockmatrix import BlockMatrix
    from matrel_tpu.core.coo import COOMatrix
    shape = tuple(old.shape)

    def _coo(rows, cols, vals=None):
        rows = np.asarray(rows, np.int64).ravel()
        cols = np.asarray(cols, np.int64).ravel()
        if vals is None:
            vals = np.ones(rows.shape, np.float32)
        vals = np.asarray(vals, np.float32).ravel()
        if rows.shape != cols.shape or rows.shape != vals.shape:
            raise ValueError("coo delta needs equal-length "
                             "rows/cols/vals")
        if rows.size and (rows.min() < 0 or rows.max() >= shape[0]
                          or cols.min() < 0 or cols.max() >= shape[1]):
            raise ValueError(
                f"coo delta indices out of bounds for {shape}")
        integral = bool(np.all(vals == np.round(vals)))
        return MatrixDelta(kind="coo", shape=shape, rows=rows,
                           cols=cols, vals=vals, integral=integral)

    def _lowrank(u, v):
        u = np.asarray(u, np.float32)
        v = np.asarray(v, np.float32)
        if u.ndim != 2 or v.ndim != 2 or u.shape[1] != v.shape[1] \
                or u.shape[0] != shape[0] or v.shape[0] != shape[1]:
            raise ValueError(
                f"lowrank delta needs U:({shape[0]},c) V:({shape[1]},c)"
                f"; got {u.shape}, {v.shape}")
        integral = bool(np.all(u == np.round(u))
                        and np.all(v == np.round(v)))
        return MatrixDelta(kind="lowrank", shape=shape, u=u, v=v,
                           integral=integral)

    def _dense(arr):
        if isinstance(arr, BlockMatrix):
            arr = arr.to_numpy()
        arr = np.asarray(arr, np.float32)
        if arr.shape != shape:
            raise ValueError(
                f"dense delta shape {arr.shape} != bound {shape}")
        integral = bool(np.all(arr == np.round(arr)))
        return MatrixDelta(kind="dense", shape=shape, dense=arr,
                           integral=integral)

    if isinstance(payload, COOMatrix):
        if tuple(payload.shape) != shape:
            raise ValueError(
                f"coo delta shape {payload.shape} != bound {shape}")
        return _coo(payload.rows, payload.cols, payload.vals)
    if isinstance(payload, MatrixDelta):
        return payload
    if kind == "coo":
        return _coo(*payload)
    if kind == "lowrank":
        return _lowrank(*payload)
    if kind == "dense":
        return _dense(payload)
    if kind != "auto":
        raise ValueError(f"unknown delta kind {kind!r} (expected "
                         f"'auto'/'coo'/'lowrank'/'dense')")
    if isinstance(payload, (tuple, list)):
        if len(payload) == 3:
            return _coo(*payload)
        if len(payload) == 2:
            a = np.asarray(payload[0])
            b = np.asarray(payload[1])
            if a.ndim == 2 and b.ndim == 2 and a.shape[1] == b.shape[1]:
                return _lowrank(a, b)
            if a.ndim == 1 and b.ndim == 1:
                return _coo(a, b)
        raise ValueError(
            "ambiguous delta payload — pass kind='coo' or 'lowrank'")
    return _dense(payload)


# ---------------------------------------------------------------------------
# Structural helpers
# ---------------------------------------------------------------------------


def _attr_tok(v) -> str:
    if v is None or isinstance(v, (bool, int, float, str)):
        return repr(v)
    if isinstance(v, (tuple, list)):
        return "[" + ",".join(_attr_tok(x) for x in v) + "]"
    return f"obj:{id(v)}"


def core_key(e: MatExpr, target_ids: frozenset) -> str:
    """Generation-invariant structural key: like the session's plan
    key, but the CHANGED matrix's leaves normalize to ``@T`` — so the
    same logical query over successive bindings of one catalog name
    keys identically, which is what lets the ``known`` map (and the
    patch-plan cache) match siblings across delta generations."""
    parts: List[str] = []

    def walk(n: MatExpr):
        if n.kind in ("leaf", "sparse_leaf", "coo_leaf"):
            m = n.attrs["matrix"]
            tok = "@T" if id(m) in target_ids else str(id(m))
            role = n.attrs.get("ivm_role")
            if role is not None:
                tok = f"@{role[0]}"
            parts.append(f"{n.kind}:{tok}:{n.shape}")
            return
        attrs = ",".join(f"{k}={_attr_tok(v)}"
                         for k, v in sorted(n.attrs.items()))
        parts.append(f"{n.kind}:{n.shape}:{attrs}(")
        for c in n.children:
            walk(c)
        parts.append(")")

    walk(e)
    return "|".join(parts)


def substitute(e: MatExpr, old, repl) -> MatExpr:
    """Replace every leaf bound to ``old`` (by identity) with a
    same-kind leaf over ``repl`` (a matrix) or with ``repl`` itself
    (a prepared MatExpr leaf). Interior structure and attrs are
    preserved — the substituted tree keys structurally identically to
    a fresh query over the new binding."""
    def walk(n: MatExpr) -> MatExpr:
        if n.kind in ("leaf", "sparse_leaf", "coo_leaf"):
            if n.attrs["matrix"] is old:
                if isinstance(repl, MatExpr):
                    return repl
                a = dict(n.attrs)
                a["matrix"] = repl
                return dataclasses.replace(n, attrs=a, nnz=getattr(
                    repl, "nnz", n.nnz), uid=next(E._ids))
            return n
        kids = tuple(walk(c) for c in n.children)
        if all(k is c for k, c in zip(kids, n.children)):
            return n
        return n.with_children(kids)

    return walk(e)


def depends_on(e: MatExpr, target_ids: frozenset,
               memo: Optional[dict] = None) -> bool:
    """Does the subtree read any leaf bound to a changed matrix?"""
    memo = memo if memo is not None else {}
    got = memo.get(e.uid)
    if got is not None:
        return got
    if e.kind in ("leaf", "sparse_leaf", "coo_leaf"):
        out = id(e.attrs["matrix"]) in target_ids
    else:
        out = any(depends_on(c, target_ids, memo) for c in e.children)
    memo[e.uid] = out
    return out


def estimate_flops(e: MatExpr,
                   config: Optional[MatrelConfig] = None,
                   memo: Optional[dict] = None) -> float:
    """Closed-form FLOP estimate of an expression — the patch-vs-
    recompute pricing input (``delta_est_saved_flops``). S×S matmuls
    that would dispatch the tile-intersection SpGEMM are priced by the
    dispatch's own pair estimate (executor.spgemm_estimates), so a
    sparse ΔA·B patch is credited the way it will actually lower."""
    cfg = config or default_config()
    memo = memo if memo is not None else {}

    def walk(n: MatExpr) -> float:
        if n.uid in memo:
            return 0.0            # shared DAG node: count once
        memo[n.uid] = True
        own = 0.0
        nm = float(n.shape[0]) * float(n.shape[1])
        if n.kind == "matmul":
            a, b = n.children
            own = 2.0 * a.shape[0] * a.shape[1] * b.shape[1]
            if a.kind in ("sparse_leaf", "coo_leaf") \
                    and b.kind in ("sparse_leaf", "coo_leaf"):
                from matrel_tpu import executor as executor_lib
                if executor_lib._spgemm_dispatch(n, cfg):
                    est = executor_lib.spgemm_estimates(n, cfg)
                    bs = est.get("block_size") or cfg.block_size
                    own = 2.0 * max(est.get("est_pairs") or 1.0, 1.0) \
                        * float(bs) ** 3
        elif n.kind == "agg":
            # a reduction READS its child, the output is the cheap
            # part — costing the (n,1) output made rowSum(A) look
            # free and priced every aggregate patch out
            c = n.children[0]
            own = float(c.shape[0]) * float(c.shape[1])
        elif n.kind in ("elemwise", "scalar", "select_value",
                        "select_index", "join_index", "rank1"):
            own = nm
        elif n.kind in ("inverse", "solve"):
            own = float(n.children[0].shape[0]) ** 3
        return own + sum(walk(c) for c in n.children)

    return walk(e)


def _optimized_flops(e: MatExpr, mesh,
                     config: Optional[MatrelConfig] = None) -> float:
    """:func:`estimate_flops` on the OPTIMIZED tree — both sides of
    the patch-vs-recompute comparison compile through the optimizer
    (R2/R3 thin the factored aggregates, the chain DP re-associates
    (V·Uᵀ)·B into V·(Uᵀ·B)), so both are priced post-optimize."""
    from matrel_tpu.core import mesh as mesh_lib
    from matrel_tpu.ir import rules as rules_lib
    cfg = config or default_config()
    try:
        opt = rules_lib.optimize(e, cfg,
                                 grid=mesh_lib.mesh_grid_shape(mesh),
                                 mesh=mesh)
    except Exception:           # pricing must never fail a register —
        opt = e                 # the raw tree is a safe overestimate
    return estimate_flops(opt, cfg)


# ---------------------------------------------------------------------------
# Patch derivation
# ---------------------------------------------------------------------------


#: Dynamic-leaf roles a patch plan rebinds across generations
#: (serve/ivm.py resolves them against the live context).
ROLE_FACTOR_U = ("factor_u",)
ROLE_FACTOR_V = ("factor_v",)
ROLE_DELTA_DENSE = ("delta_dense",)
ROLE_DELTA_SPARSE = ("delta_sparse",)
ROLE_TARGET_OLD = ("target_old",)
ROLE_TARGET_NEW = ("target_new",)
ROLE_OLD_RESULT = ("old_result",)


def _role_leaf(bm, role: tuple) -> MatExpr:
    """A leaf tagged with its rebind role (the ``ivm_role`` attr rides
    the plan's leaf_order so serve/ivm.py can rebind by role)."""
    kind = type(bm).__name__
    if kind == "BlockSparseMatrix":
        return bm.expr().with_attrs(ivm_role=role)
    return E.leaf(bm).with_attrs(ivm_role=role)


@dataclasses.dataclass
class PatchSpec:
    """One derivable patch: either an expression computing the PATCHED
    result directly (``old_result + Δf``, one compiled plan), or an
    iterative ``refine`` callable (the warm-restart family)."""

    rule: str                                 # DELTA_RULES member
    rules: Dict[str, int]                     # per-rule census
    est_patch_flops: float
    est_full_flops: float
    err_bound: float                          # bound ADDED by the patch
    expr: Optional[MatExpr] = None
    refine: Optional[Callable] = None
    rebindable: bool = True                   # factor/dense roles only
    known_keys: Tuple[str, ...] = ()          # sibling deps of the plan

    @property
    def est_saved_flops(self) -> float:
        return self.est_full_flops - self.est_patch_flops


class _Ctx:
    def __init__(self, old, new, delta: MatrixDelta, mesh, config,
                 known: Optional[dict]):
        self.old = old
        self.new = new
        self.delta = delta
        self.mesh = mesh
        self.config = config
        self.target_ids = frozenset({id(old)})
        self.known = known or {}
        self.census: Dict[str, int] = {}
        self.max_k = 0
        self.rebindable = True
        self.known_used: List[str] = []
        self.dep_memo: dict = {}

    def count(self, rule: str):
        self.census[rule] = self.census.get(rule, 0) + 1


def _delta_product(ctx: _Ctx, partner: MatExpr, side: str
                   ) -> Optional[MatExpr]:
    """ΔA·partner (side="left") or partner·ΔA (side="right") in the
    cheapest available form: sparse×sparse through the SpGEMM dispatch,
    else the thin factored product, else the dense delta leaf."""
    d = ctx.delta
    # S×S: the sparse delta against a sparse partner leaf is a native
    # SpGEMM through the PR 10 registry — consult the ONE dispatch
    # predicate so we only take this form when it will actually fire
    if partner.kind in ("sparse_leaf", "coo_leaf"):
        bs = getattr(partner.attrs["matrix"], "block_size",
                     ctx.config.block_size)
        sp = d.sparse(ctx.mesh, bs, ctx.config)
        if sp is not None:
            dleaf = _role_leaf(sp, ROLE_DELTA_SPARSE)
            node = (E.matmul(dleaf, partner) if side == "left"
                    else E.matmul(partner, dleaf))
            from matrel_tpu import executor as executor_lib
            if executor_lib._spgemm_dispatch(node, ctx.config):
                ctx.count("spgemm")
                ctx.rebindable = False    # sparse payloads trace as
                return node               # constants — not rebindable
    fac = d.factors(ctx.mesh, ctx.config)
    if fac is not None:
        u, v = fac
        ul = _role_leaf(u, ROLE_FACTOR_U)
        vl = _role_leaf(v, ROLE_FACTOR_V)
        ctx.count("rank_k")
        ctx.max_k = max(ctx.max_k, u.shape[1], partner.shape[0],
                        partner.shape[1])
        if side == "left":
            # (U·Vᵀ)·B emitted pre-associated as U·(Vᵀ·B): the thin
            # ordering is the ESTIMATE, not a hope about the chain DP
            return E.matmul(ul, E.matmul(E.transpose(vl), partner))
        return E.matmul(E.matmul(partner, ul), E.transpose(vl))
    dl = _role_leaf(d.materialize(ctx.mesh, ctx.config),
                    ROLE_DELTA_DENSE)
    ctx.count("linear")
    node = (E.matmul(dl, partner) if side == "left"
            else E.matmul(partner, dl))
    ctx.max_k = max(ctx.max_k, partner.shape[0], partner.shape[1])
    return node


def _delta_leafwise(ctx: _Ctx, form: str = "factored") -> MatExpr:
    """ΔA as a same-shaped expression. ``form`` is the CONSUMER's
    preference: aggregate consumers want the FACTORED product ``U·Vᵀ``
    (they thin out through R3: ``rowSum(U·Vᵀ) → U·rowSum(Vᵀ)``, and
    the factor leaves stay rebindable); elementwise consumers want the
    dense materialization (a leaf costs nothing extra — the factored
    product would ADD an n·m·c multiply just to feed a pointwise op).
    Both fall back to the other when their form is unavailable."""
    fac = (ctx.delta.factors(ctx.mesh, ctx.config)
           if form == "factored" else None)
    if fac is not None:
        u, v = fac
        ctx.count("rank_k")
        ctx.max_k = max(ctx.max_k, u.shape[1])
        return E.matmul(_role_leaf(u, ROLE_FACTOR_U),
                        E.transpose(_role_leaf(v, ROLE_FACTOR_V)))
    ctx.count("linear")
    return _role_leaf(ctx.delta.materialize(ctx.mesh, ctx.config),
                      ROLE_DELTA_DENSE)


def _value_at(ctx: _Ctx, n: MatExpr, binding: str) -> MatExpr:
    """The subtree's VALUE at the old/new binding, cheapest first: a
    known sibling entry's materialized result as a leaf, else the tree
    itself with the target leaf swapped to the requested binding
    (re-evaluated inside the patch plan — priced honestly)."""
    ck = core_key(n, ctx.target_ids)
    hit = ctx.known.get(ck)
    if hit is not None:
        old_bm, new_bm = hit
        ctx.count("known")
        ctx.known_used.append(ck)
        bm = old_bm if binding == "old" else new_bm
        return _role_leaf(bm, ("known_" + binding, ck))
    if not depends_on(n, ctx.target_ids, ctx.dep_memo):
        return n
    if binding == "old":
        return substitute(n, ctx.old,
                          _role_leaf(ctx.old, ROLE_TARGET_OLD))
    return substitute(n, ctx.old, _role_leaf(ctx.new, ROLE_TARGET_NEW))


def _add(a: Optional[MatExpr], b: Optional[MatExpr],
         op: str = "add") -> Optional[MatExpr]:
    if a is None and b is None:
        return None
    if b is None:
        return a
    if a is None:
        if op == "sub":
            return E.scalar_op("mul", b, -1.0)
        return b
    return E.elemwise(op, a, b)


def _derive(ctx: _Ctx, n: MatExpr,
            form: str = "factored") -> Optional[MatExpr]:
    """Δ of a subtree under the registered update, or None for a
    structural zero (``form`` is the consuming context's preferred
    delta-leaf shape — see :func:`_delta_leafwise`). Raises
    :class:`DeltaIneligible` where no rule applies — the caller falls
    back to the transitive kill."""
    if not depends_on(n, ctx.target_ids, ctx.dep_memo):
        return None
    ck = core_key(n, ctx.target_ids)
    hit = ctx.known.get(ck)
    if hit is not None:
        # a sibling cached entry already carries this subtree's old
        # AND patched values — its delta enters as a leaf difference
        # instead of a re-derivation (propagation through the DAG)
        old_bm, new_bm = hit
        ctx.count("known")
        ctx.known_used.append(ck)
        return E.elemwise("sub",
                          _role_leaf(new_bm, ("known_new", ck)),
                          _role_leaf(old_bm, ("known_old", ck)))
    kind = n.kind
    if kind in ("leaf", "sparse_leaf", "coo_leaf"):
        return _delta_leafwise(ctx, form)
    if kind == "transpose":
        d = _derive(ctx, n.children[0], form)
        return None if d is None else E.transpose(d)
    if kind == "matmul":
        a, b = n.children
        a_dep = depends_on(a, ctx.target_ids, ctx.dep_memo)
        b_dep = depends_on(b, ctx.target_ids, ctx.dep_memo)
        # the sided fast forms when the changed operand IS the leaf:
        # emit the thin/sparse product directly
        terms: List[Optional[MatExpr]] = []
        if a_dep and not b_dep:
            if a.kind in ("leaf", "sparse_leaf", "coo_leaf"):
                return _delta_product(ctx, _value_at(ctx, b, "old"),
                                      "left")
            da = _derive(ctx, a)
            return None if da is None else E.matmul(
                da, _value_at(ctx, b, "old"))
        if b_dep and not a_dep:
            if b.kind in ("leaf", "sparse_leaf", "coo_leaf"):
                return _delta_product(ctx, _value_at(ctx, a, "old"),
                                      "right")
            db = _derive(ctx, b)
            return None if db is None else E.matmul(
                _value_at(ctx, a, "old"), db)
        # both sides change: Δ(a·b) = Δa·b_old + a_new·Δb (exact —
        # the Gram / linreg rank-k correction when a = bᵀ)
        ctx.count("rank_k_both")
        if a.kind in ("leaf", "sparse_leaf", "coo_leaf"):
            da_b = _delta_product(ctx, _value_at(ctx, b, "old"), "left")
        else:
            da = _derive(ctx, a)
            da_b = None if da is None else E.matmul(
                da, _value_at(ctx, b, "old"))
        if b.kind in ("leaf", "sparse_leaf", "coo_leaf"):
            a_db = _delta_product(ctx, _value_at(ctx, a, "new"),
                                  "right")
        else:
            db = _derive(ctx, b)
            a_db = None if db is None else E.matmul(
                _value_at(ctx, a, "new"), db)
        terms = [da_b, a_db]
        out = None
        for t in terms:
            out = _add(out, t)
        return out
    if kind == "elemwise":
        op = n.attrs["op"]
        a, b = n.children
        if a.shape != b.shape:
            # broadcast deltas are shape-ambiguous; keep the exact lane
            raise DeltaIneligible(f"broadcast elemwise {op}")
        if op in ("add", "sub"):
            return _add(_derive(ctx, a, "dense"),
                        _derive(ctx, b, "dense"), op)
        if op == "mul":
            da = _derive(ctx, a, "dense")
            db = _derive(ctx, b, "dense")
            t1 = None if da is None else E.elemwise(
                "mul", da, _value_at(ctx, b, "old"))
            t2 = None if db is None else E.elemwise(
                "mul", _value_at(ctx, a, "new"), db)
            return _add(t1, t2)
        if op == "div":
            if depends_on(b, ctx.target_ids, ctx.dep_memo):
                raise DeltaIneligible("div by a changed operand")
            da = _derive(ctx, a, "dense")
            return None if da is None else E.elemwise(
                "div", da, _value_at(ctx, b, "old"))
        raise DeltaIneligible(f"elemwise {op} is not linear")
    if kind == "scalar":
        op = n.attrs["op"]
        d = _derive(ctx, n.children[0], form)
        if d is None:
            return None
        if op == "mul":
            return E.scalar_op("mul", d, n.attrs["value"])
        if op == "add":
            return d
        raise DeltaIneligible("scalar pow is not linear")
    if kind == "agg":
        agg_kind, axis = n.attrs["agg"], n.attrs["axis"]
        if agg_kind not in ("sum", "avg"):
            raise DeltaIneligible(f"agg {agg_kind} is not linear")
        d = _derive(ctx, n.children[0], "factored")
        return None if d is None else E.agg(d, agg_kind, axis)
    if kind == "vec":
        d = _derive(ctx, n.children[0], "factored")
        return None if d is None else E.vec(d)
    if kind == "rank1":
        base, u, v = n.children
        if depends_on(u, ctx.target_ids, ctx.dep_memo) or \
                depends_on(v, ctx.target_ids, ctx.dep_memo):
            raise DeltaIneligible("rank1 with changed u/v")
        return _derive(ctx, base)
    raise DeltaIneligible(f"no delta rule for node kind {kind!r}")


def derive_patch(expr: MatExpr, old, new, delta: MatrixDelta,
                 old_result, mesh,
                 config: Optional[MatrelConfig] = None,
                 known: Optional[dict] = None) -> Optional[PatchSpec]:
    """Derive the patch for one cached entry ``old_result = expr`` (a
    tree over the OLD binding) under ``old → new = old + delta``.

    Returns None when no rule applies (the caller falls back to the
    transitive kill). ``known`` maps :func:`core_key` strings of
    sibling cached entries to their ``(old_result, patched_result)``
    BlockMatrices — the delta-propagation substrate."""
    cfg = config or default_config()
    refine = expr.attrs.get("delta_refine")
    est_full = _optimized_flops(expr, mesh, cfg)
    if callable(refine):
        # the iterative family (PageRank warm restart): re-solve from
        # the cached value instead of algebraic patching; the stamped
        # cost estimate (or a documented fraction) prices it
        est_patch = float(expr.attrs.get("delta_refine_flops")
                          or est_full * 0.25)
        return PatchSpec(rule="refine", rules={"refine": 1},
                         est_patch_flops=est_patch,
                         est_full_flops=est_full,
                         err_bound=float(
                             expr.attrs.get("delta_refine_bound")
                             or 0.0),
                         refine=refine, rebindable=False)
    ctx = _Ctx(old, new, delta, mesh, cfg, known)
    try:
        d = _derive(ctx, expr)
    except DeltaIneligible:
        return None
    base = _role_leaf(old_result, ROLE_OLD_RESULT)
    patched = base if d is None else E.elemwise("add", base, d)
    census = dict(ctx.census)
    if ctx.census.get("spgemm"):
        rule = "spgemm"
    elif ctx.census.get("rank_k_both"):
        rule = "rank_k_both"
    elif ctx.census.get("rank_k"):
        rule = "rank_k"
    else:
        rule = "linear"
    # exact iff the QUERY is provably integer-valued (ir/stats'
    # integer-exactness inference — the PR 7 int-path proof) AND the
    # delta is: integer patches of integer views compose exactly, so
    # graph-count maintenance asserts bit equality (err bound 0)
    from matrel_tpu.ir import stats as stats_lib
    memo: dict = {}
    amax = stats_lib.integral_abs_bound(expr, memo)
    exact = bool(delta.integral
                 and (np.issubdtype(np.dtype(old_result.dtype),
                                    np.integer)
                      or (stats_lib.infer_integral(expr, memo)
                          # f32's contiguous-integer range: above it
                          # integer arithmetic in f32 rounds, so the
                          # "exact" claim needs the magnitude proof
                          # too (the int-tier overflow gate's rule)
                          and amax is not None
                          and amax <= 2.0 ** 24)))
    # error-bound composition (docs/IVM.md): one f32 product unit per
    # contraction depth the patch adds, plus one for the combine —
    # integer-exact patches contribute zero (the int paths are exact)
    bound = 0.0 if exact else _F32_EPS * float(max(ctx.max_k, 1) + 1)
    est_patch = _optimized_flops(patched, mesh, cfg)
    return PatchSpec(rule=rule, rules=census,
                     est_patch_flops=est_patch,
                     est_full_flops=est_full,
                     err_bound=bound, expr=patched,
                     rebindable=ctx.rebindable,
                     known_keys=tuple(sorted(set(ctx.known_used))))


# ---------------------------------------------------------------------------
# Iterative refinement — the PageRank warm restart
# ---------------------------------------------------------------------------


def pagerank_warm_restart(adj: np.ndarray, r0: np.ndarray,
                          alpha: float = 0.85, rounds: int = 8,
                          tol: float = 1e-10) -> np.ndarray:
    """Power-iteration PageRank over a (possibly updated) adjacency,
    STARTED from a cached rank vector instead of uniform — for a small
    ΔA the cached vector is already near the new fixed point, so a
    handful of rounds recovers what a cold start pays tens for (the
    iterative member of the delta-rule family; docs/IVM.md)."""
    n = adj.shape[0]
    deg = adj.sum(axis=1)
    w = np.where(deg > 0, 1.0 / np.maximum(deg, 1.0), 0.0)
    dangling = (deg == 0).astype(np.float64)
    r = np.asarray(r0, np.float64).reshape(-1)
    s = r.sum()
    if s > 0:
        r = r / s
    for _ in range(max(rounds, 1)):
        contrib = adj.T @ (w * r)
        dmass = float(dangling @ r) / n
        nxt = alpha * (contrib + dmass) + (1.0 - alpha) / n
        if float(np.abs(nxt - r).sum()) < tol:
            r = nxt
            break
        r = nxt
    return r


def stamp_refine(expr: MatExpr, fn: Callable,
                 est_flops: Optional[float] = None,
                 err_bound: float = 0.0) -> MatExpr:
    """Stamp an expression with an iterative-refinement rule: on a
    registered delta, the plane calls ``fn(old_result, new_matrix,
    delta) -> BlockMatrix | ndarray`` instead of deriving an algebraic
    patch. The workload owns convergence; MV113's dynamic check still
    proves the refined result against fresh execution."""
    attrs = {"delta_refine": fn, "delta_refine_bound": float(err_bound)}
    if est_flops is not None:
        attrs["delta_refine_flops"] = float(est_flops)
    return expr.with_attrs(**attrs)


def delta_prefix(gen: int) -> str:
    """The result-cache key prefix of delta generation ``gen`` — the
    ``degr:``/``axisw:``/``prec:`` idiom: generation 0 (the delta
    plane never used) keeps the historical key format bit-identically;
    every later generation isolates its entries, so a patched result
    from generation N can never answer a query at N+1 without being
    re-patched or re-executed."""
    return "" if gen <= 0 else f"delta:{gen}|"
