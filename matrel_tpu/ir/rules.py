"""Algebraic rewrite rules — the MatfastOptimizer rule batch
(SURVEY.md §2 "Optimizer: rewrite rules", §3.2).

Rules, mirroring the reference's Catalyst batch:
  R1 double-transpose elimination:      (Aᵀ)ᵀ → A
  R2 transpose push-down:               (A·B)ᵀ → Bᵀ·Aᵀ ;
     (A+B)ᵀ → Aᵀ+Bᵀ ; (sA)ᵀ → s(Aᵀ) ; vec/agg interplay
  R3 aggregation push-down into multiply:
     rowSum(A·B) → A·rowSum(B) ; colSum(A·B) → colSum(A)·B
     sum(A·B)    → colSum(A)·rowSum(B)
     trace(A·B)  → sum(A ⊙ Bᵀ)
     rowSum(Aᵀ)  → colSum(A)ᵀ ; colSum(Aᵀ) → rowSum(A)ᵀ
     sum(sA)     → s·sum(A) ; sum(A+B) → sum(A)+sum(B)
  R4 scalar folding: s1·(s2·A) → (s1·s2)·A ; s1+(s2+A) → (s1+s2)+A ;
     1·A → A ; 0+A → A
  R5 selection push-down: index-σ commutes through elementwise ops and
     transposes (σ_rows through transpose becomes σ_cols).
  R6 matrix-chain DP reorder (chain.py), run after the structure-exposing
     rules above.
  R7 solve fusion: A⁻¹·B → solve(A,B) ; A·B⁻¹ → solve(Bᵀ,Aᵀ)ᵀ ;
     (A⁻¹)⁻¹ → A — the normal-equations pattern (XᵀX)⁻¹·Xᵀy never
     materialises an inverse.
  R8 rank-1 multiply push-through: (A + u·vᵀ)·B → A·B + u·(vᵀ·B) and
     B·(A + u·vᵀ) → B·A + (B·u)·vᵀ — the outer product is never
     materialised inside a multiply chain (MatFast's rank-1 family).

Each rule is a bottom-up tree transform; the batch runs to fixpoint with a
bound, Catalyst-style.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from matrel_tpu.config import MatrelConfig, default_config
from matrel_tpu.ir import chain as chain_lib
from matrel_tpu.ir.expr import (
    MatExpr, agg, elemwise, matmul, scalar_op, select_index, solve,
    transpose,
)

Rule = Callable[[MatExpr], Optional[MatExpr]]


def _rewrite_bottom_up(e: MatExpr, rule: Rule,
                       counts: Optional[dict] = None) -> MatExpr:
    new_children = tuple(_rewrite_bottom_up(c, rule, counts)
                         for c in e.children)
    if any(nc is not oc for nc, oc in zip(new_children, e.children)):
        e = e.with_children(new_children)
    out = rule(e)
    if out is not None and counts is not None:
        # per-rule hit counter — the observability feed (obs/ event
        # records carry these, the SparkListener rule-metrics analogue)
        name = getattr(rule, "__name__", str(rule))
        counts[name] = counts.get(name, 0) + 1
    return out if out is not None else e


# -- R1/R2: transpose rules -------------------------------------------------


def transpose_rules(e: MatExpr) -> Optional[MatExpr]:
    if e.kind != "transpose":
        return None
    (c,) = e.children
    if c.kind == "transpose":  # (Aᵀ)ᵀ → A
        return c.children[0]
    if c.kind == "matmul":  # (A·B)ᵀ → Bᵀ·Aᵀ
        a, b = c.children
        return matmul(transpose(b), transpose(a))
    if c.kind == "elemwise":  # (A∘B)ᵀ → Aᵀ∘Bᵀ  (shapes must match exactly)
        a, b = c.children
        if a.shape == b.shape:
            return elemwise(c.attrs["op"], transpose(a), transpose(b))
        return None
    if c.kind == "scalar":  # (s∘A)ᵀ → s∘(Aᵀ)
        return scalar_op(c.attrs["op"], transpose(c.children[0]), c.attrs["value"])
    if c.kind == "agg":
        # rowSumᵀ/colSumᵀ still just a vector; transposing agg output is
        # cheap — leave in place.
        return None
    return None


# -- R3: aggregation push-down ---------------------------------------------


def agg_pushdown(e: MatExpr) -> Optional[MatExpr]:
    if e.kind != "agg":
        return None
    kind, axis = e.attrs["agg"], e.attrs["axis"]
    (c,) = e.children
    if kind != "sum":
        return None  # max/min/count/avg don't distribute over matmul
    if c.kind == "matmul":
        a, b = c.children
        if axis == "row":   # rowSum(A·B) = A · rowSum(B)
            return matmul(a, agg(b, "sum", "row"))
        if axis == "col":   # colSum(A·B) = colSum(A) · B
            return matmul(agg(a, "sum", "col"), b)
        if axis == "all":   # sum(A·B) = colSum(A) · rowSum(B)
            return matmul(agg(a, "sum", "col"), agg(b, "sum", "row"))
        if axis == "diag":  # trace(A·B) = sum(A ⊙ Bᵀ)
            if a.shape == (b.shape[1], b.shape[0]):
                return agg(elemwise("mul", a, transpose(b)), "sum", "all")
        return None
    if c.kind == "transpose":
        inner = c.children[0]
        if axis == "row":   # rowSum(Aᵀ) = colSum(A)ᵀ
            return transpose(agg(inner, "sum", "col"))
        if axis == "col":
            return transpose(agg(inner, "sum", "row"))
        if axis in ("all", "diag"):  # invariant under transpose
            return agg(inner, "sum", axis)
        return None
    if c.kind == "scalar" and c.attrs["op"] == "mul":
        # sum(s·A) = s·sum(A) — shrink before scaling
        return scalar_op("mul", agg(c.children[0], "sum", axis), c.attrs["value"])
    if c.kind == "elemwise" and c.attrs["op"] in ("add", "sub") \
            and c.children[0].shape == c.children[1].shape:
        a, b = c.children
        return elemwise(c.attrs["op"], agg(a, "sum", axis), agg(b, "sum", axis))
    if c.kind == "rank1":
        # rowSum(A + u·vᵀ) = rowSum(A) + u·sum(v)   (MatFast's rank-1
        # update rules: never materialise the outer product for aggregates)
        a, u, v = c.children
        if axis == "row":
            return elemwise("add", agg(a, "sum", "row"),
                            matmul(u, agg(v, "sum", "all")))
        if axis == "col":
            return elemwise("add", agg(a, "sum", "col"),
                            matmul(agg(u, "sum", "all"), transpose(v)))
        if axis == "all":
            # sum(u·vᵀ) = sum(u)·sum(v)
            return elemwise("add", agg(a, "sum", "all"),
                            matmul(agg(u, "sum", "all"), agg(v, "sum", "all")))
    return None


# -- R4: scalar folding -----------------------------------------------------


def scalar_folding(e: MatExpr) -> Optional[MatExpr]:
    if e.kind != "scalar":
        return None
    op, v = e.attrs["op"], e.attrs["value"]
    (c,) = e.children
    if op == "mul" and v == 1.0:
        return c
    if op == "add" and v == 0.0:
        return c
    if op == "pow" and v == 1.0:
        return c
    if c.kind == "scalar" and c.attrs["op"] == op and op in ("mul", "add"):
        merged = v * c.attrs["value"] if op == "mul" else v + c.attrs["value"]
        return scalar_op(op, c.children[0], merged)
    return None


# -- R5: selection push-down ------------------------------------------------


def selection_pushdown(e: MatExpr) -> Optional[MatExpr]:
    if e.kind != "select_index":
        return None
    rows, cols = e.attrs["rows"], e.attrs["cols"]
    (c,) = e.children
    if c.kind == "transpose":
        # σ_rows(Aᵀ) = (σ_cols(A))ᵀ
        return transpose(select_index(c.children[0], rows=cols, cols=rows))
    if c.kind == "elemwise" and c.children[0].shape == c.children[1].shape:
        a, b = c.children
        return elemwise(
            c.attrs["op"],
            select_index(a, rows=rows, cols=cols),
            select_index(b, rows=rows, cols=cols),
        )
    if c.kind == "scalar" and c.attrs["op"] == "mul":
        return scalar_op("mul",
                         select_index(c.children[0], rows=rows, cols=cols),
                         c.attrs["value"])
    if c.kind == "matmul":
        # σ over rows touches only A's rows; over cols only B's cols:
        # σ_r,c(A·B) = σ_r(A) · σ_c(B)
        a, b = c.children
        if rows is not None or cols is not None:
            na = select_index(a, rows=rows, cols=None) if rows is not None else a
            nb = select_index(b, rows=None, cols=cols) if cols is not None else b
            if na is not a or nb is not b:
                return matmul(na, nb)
    return None


# -- R8: rank-1 multiply push-through ----------------------------------------


def rank1_pushdown(e: MatExpr) -> Optional[MatExpr]:
    """(A + u·vᵀ)·B → A·B + u·(vᵀ·B) ; B·(A + u·vᵀ) → B·A + (B·u)·vᵀ.

    MatFast's rank-1 family: never materialise the n×m outer product
    inside a multiply chain — the rewritten form costs two thin
    matmuls and an add, and exposes A·B to the chain DP. Always a win
    for genuine rank-1 updates (u: n×1, v: m×1)."""
    if e.kind != "matmul":
        return None
    a, b = e.children
    if a.kind == "rank1":
        base, u, v = a.children
        return elemwise("add", matmul(base, b),
                        matmul(u, matmul(transpose(v), b)))
    if b.kind == "rank1":
        base, u, v = b.children
        return elemwise("add", matmul(a, base),
                        matmul(matmul(a, u), transpose(v)))
    return None


# -- R7: solve fusion --------------------------------------------------------


def solve_fusion(e: MatExpr) -> Optional[MatExpr]:
    """A⁻¹·B → solve(A, B); A·B⁻¹ → solve(Bᵀ, Aᵀ)ᵀ; (A⁻¹)⁻¹ → A.

    The reference's normal-equations workload writes (XᵀX)⁻¹·(Xᵀy); an
    explicit inverse materialises n² solve results to use n·m of them
    and is less numerically stable than LU-solving against B directly.
    """
    if e.kind == "inverse" and e.children[0].kind == "inverse":
        return e.children[0].children[0]
    if e.kind != "matmul":
        return None
    a, b = e.children
    if a.kind == "inverse":
        return solve(a.children[0], b)
    if b.kind == "inverse":
        return transpose(solve(transpose(b.children[0]), transpose(a)))
    return None


_RULES: List[Rule] = [
    transpose_rules,
    agg_pushdown,
    scalar_folding,
    selection_pushdown,
    solve_fusion,
    rank1_pushdown,
]

_MAX_ITERS = 10


def apply_rewrites(e: MatExpr,
                   counts: Optional[dict] = None) -> MatExpr:
    """Run the rule batch to fixpoint (bounded, Catalyst-style).
    ``counts`` (optional) accumulates per-rule hit counts."""
    for _ in range(_MAX_ITERS):
        before = e
        for rule in _RULES:
            e = _rewrite_bottom_up(e, rule, counts)
        if _same_structure(e, before):
            break
    return e


def _same_structure(a: MatExpr, b: MatExpr) -> bool:
    if a is b:
        return True
    if a.kind != b.kind or a.shape != b.shape or len(a.children) != len(b.children):
        return False
    # compare ALL attrs (not a fixed whitelist — a rule rewriting an
    # attr outside a whitelist would fool fixpoint detection into an
    # early exit); callables and other unhashables compare by identity
    keys = set(a.attrs) | set(b.attrs)
    for k in keys:
        va, vb = a.attrs.get(k), b.attrs.get(k)
        if isinstance(va, (int, float, str, bool, type(None))) \
                and isinstance(vb, (int, float, str, bool, type(None))):
            if va != vb:
                return False
        elif va is not vb:
            return False
    return all(_same_structure(x, y) for x, y in zip(a.children, b.children))


def common_subexpressions(e: MatExpr) -> MatExpr:
    """Hash-consing: structurally identical subtrees collapse to ONE node,
    so the executor's identity-keyed memo computes them once (the analogue
    of Catalyst's plan normalization + Spark's reused-exchange). Callable
    attrs (predicates/merges) key by identity."""
    table: dict = {}

    def key_of(n: MatExpr, child_keys) -> tuple:
        attr_items = []
        for k, v in sorted(n.attrs.items()):
            if callable(v) or not isinstance(v, (int, float, str, bool,
                                                 type(None))):
                attr_items.append((k, id(v)))
            else:
                attr_items.append((k, v))
        return (n.kind, n.shape, tuple(attr_items), tuple(child_keys))

    def walk(n: MatExpr) -> tuple:
        child_pairs = [walk(c) for c in n.children]
        child_keys = [k for k, _ in child_pairs]
        new_children = tuple(c for _, c in child_pairs)
        k = key_of(n, child_keys)
        if k in table:
            return k, table[k]
        if any(nc is not oc for nc, oc in zip(new_children, n.children)):
            n = n.with_children(new_children)
        table[k] = n
        return k, n

    return walk(e)[1]


def optimize(e: MatExpr, config: Optional[MatrelConfig] = None,
             grid: tuple = (1, 1), mesh=None,
             counts: Optional[dict] = None) -> MatExpr:
    """Full logical optimization: rewrites, chain-DP reorder, CSE.
    ``grid`` is the mesh grid shape — the chain DP's step cost then
    includes each candidate multiply's collective bill (comm-aware
    reorder); (1, 1) keeps the pure-FLOPs DP. ``mesh`` makes the bill
    layout-aware (round 5): operand PartitionSpecs steer the reorder.
    ``counts`` (optional) accumulates per-rule hit counts plus a
    ``chain_dp`` entry when the reorder restructured a chain — the
    rewrite-metrics feed of the obs/ event log."""
    cfg = config or default_config()
    if cfg.rewrite_rules:
        e = apply_rewrites(e, counts)
    if cfg.chain_opt:
        reordered = chain_lib.reorder_chains(e, grid, mesh, cfg)
        # structural comparison, not identity: reorder_chains rebuilds
        # matmul nodes even when it keeps the original parenthesisation
        if counts is not None and reordered is not e \
                and not _same_structure(reordered, e):
            counts["chain_dp"] = counts.get("chain_dp", 0) + 1
        e = reordered
        if cfg.rewrite_rules:
            e = apply_rewrites(e, counts)  # reorder can expose new folds
    if cfg.rewrite_rules:
        e = common_subexpressions(e)
    return e
