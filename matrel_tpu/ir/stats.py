"""Dimension + sparsity statistics propagation (SURVEY.md §2
"Statistics / sparsity estimation").

The reference propagates (nRows, nCols, nnz) bottom-up through the Catalyst
plan and feeds the estimates to the matrix-chain DP and physical strategy
choice. Same role here: pure-Python estimates over the MatExpr tree, no
devices involved.

Estimation model (standard independence assumptions, as in MatFast/MatRel):
  density(A·B)   ≈ 1 - (1 - dA*dB)^k   (k = contraction dim)
  density(A+B)   ≈ min(1, dA + dB)
  density(A⊙B)  ≈ dA * dB
  transpose/scalar-mul preserve density; scalar-add densifies.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple


def density_of(nnz: Optional[int], shape: Tuple[int, int]) -> float:
    if nnz is None:
        return 1.0
    n = shape[0] * shape[1]
    return min(1.0, nnz / n) if n else 0.0


def nnz_from_density(d: float, shape: Tuple[int, int]) -> int:
    return int(round(min(1.0, max(0.0, d)) * shape[0] * shape[1]))


def matmul_density(da: float, db: float, k: int) -> float:
    """Probability an output entry is nonzero given k independent trials."""
    p = da * db
    if p <= 0.0:
        return 0.0
    if p >= 1.0:
        return 1.0
    # 1-(1-p)^k, computed stably.
    return -math.expm1(k * math.log1p(-p))


def add_density(da: float, db: float) -> float:
    return min(1.0, da + db)


def elemmul_density(da: float, db: float) -> float:
    return da * db


def matmul_cost(
    n: int, k: int, m: int, da: float = 1.0, db: float = 1.0
) -> float:
    """Estimated FLOP cost of an (n×k)·(k×m) multiply.

    Sparsity-aware as in the reference's chain DP: work scales with the
    expected number of nonzero multiply-accumulate pairs.
    """
    return 2.0 * n * k * m * da * db


COMM_FLOPS_PER_BYTE = 1000.0
"""Blend factor converting ICI bytes into FLOP-equivalents for the
chain DP's step cost: a v5e chip retires ~200e12 bf16 FLOP/s against
~200 GB/s of per-link ICI, so ~1000 MXU FLOPs buy the time of one
ICI byte. Order-of-magnitude is what matters — the term breaks
FLOP-ties toward the cheaper collective bill."""


#: Layout codes shared with native/chain_dp.cc's layout-aware DP — the
#: C side receives operand layouts as int8 with exactly this mapping.
LAYOUT_CODES = {"2d": 0, "row": 1, "col": 2, "rep": 3, "other": 4}


def comm_proxy_layout(n: int, k: int, m: int, da: float, db: float,
                      gx: int, gy: int, itemsize: int = 4,
                      la: str = "2d", lb: str = "2d",
                      weights: tuple = (1.0, 1.0)
                      ) -> tuple:
    """(cheapest per-device ICI cost, output layout of the argmin
    strategy) for an (n×k)·(k×m) multiply on a gx×gy mesh — the chain
    DP's comm term, PER-LAYOUT (round 5) and now TOPOLOGY-WEIGHTED
    (round 7: ``weights`` are the per-axis inverse-bandwidth weights of
    core/mesh.MeshTopology, so the DP ranks parenthesisations by what
    their collectives cost on a hierarchical ICI/DCN mesh, not by flat
    bytes).

    Delegates to planner.comm_cost per strategy (ONE Python source of
    truth for the per-layout closed forms — review r5; the only copy is
    the C mirror in native/chain_dp.cc, equivalence-fuzzed by
    test_native) but still applies NO admissibility or broadcast-
    threshold gates (the planner picks the real strategy per multiply
    afterwards). Tie-break order (bmm_right, bmm_left, cpmm, rmm) MUST
    stay in sync with native/chain_dp.cc's comm_proxy_layout."""
    p = gx * gy
    if p <= 1:
        return 0.0, "2d"
    from matrel_tpu.parallel import planner   # lazy: no import cycle
    best, lay = None, "2d"
    for strat, out_lay in (("bmm_right", "row"), ("bmm_left", "col"),
                           ("cpmm", "2d"), ("rmm", "2d")):
        c = planner.comm_cost(strat, n, k, m, da, db, gx, gy,
                              itemsize, la, lb, weights=weights)
        if best is None or c < best:
            best, lay = c, out_lay
    return best, lay


def comm_proxy(n: int, k: int, m: int, da: float, db: float,
               gx: int, gy: int, itemsize: int = 4) -> float:
    """comm_proxy_layout at the canonical "2d" layouts — the
    layout-blind view kept for callers that predate the layout-aware
    DP (and for the native matrel_chain_dp_comm symbol's semantics)."""
    return comm_proxy_layout(n, k, m, da, db, gx, gy, itemsize)[0]


def chain_step_cost(n: int, k: int, m: int, da: float, db: float,
                    gx: int = 1, gy: int = 1) -> float:
    """DP step cost: sparsity-aware FLOPs + the collective bill in
    FLOP-equivalents. With gx·gy == 1 this is exactly matmul_cost, so
    single-device plans are unchanged."""
    return (matmul_cost(n, k, m, da, db)
            + COMM_FLOPS_PER_BYTE * comm_proxy(n, k, m, da, db, gx, gy))


def chain_step_cost_layout(n: int, k: int, m: int, da: float, db: float,
                           gx: int, gy: int, la: str, lb: str,
                           weights: tuple = (1.0, 1.0)) -> tuple:
    """(step cost, output layout): chain_step_cost with per-layout,
    topology-weighted comm terms — the layout-aware DP's step (round 5;
    weights round 7)."""
    comm, lay = comm_proxy_layout(n, k, m, da, db, gx, gy, la=la, lb=lb,
                                  weights=weights)
    return (matmul_cost(n, k, m, da, db)
            + COMM_FLOPS_PER_BYTE * comm), lay


def matmul_out_nnz(
    n: int, k: int, m: int, nnz_a: Optional[int], nnz_b: Optional[int]
) -> Optional[int]:
    if nnz_a is None and nnz_b is None:
        return None
    da = density_of(nnz_a, (n, k))
    db = density_of(nnz_b, (k, m))
    return nnz_from_density(matmul_density(da, db, k), (n, m))


# -- block-granular SpGEMM estimates (ops/spgemm.py dispatch + pricing) -----


def block_density(elem_density: float, block_size: int) -> float:
    """Probability a block_size×block_size tile holds ≥1 nonzero, under
    the same independence assumption as matmul_density — lifts an
    ELEMENT density (COO leaves) to the BLOCK granularity the SpGEMM
    tile-intersection reasons at. Same stable 1-(1-p)^k form."""
    if elem_density <= 0.0:
        return 0.0
    if elem_density >= 1.0:
        return 1.0
    return -math.expm1(block_size * block_size
                       * math.log1p(-elem_density))


def spgemm_pairs_estimate(nnzb_a: float, nnzb_b: float, kb: int) -> float:
    """Expected (A-tile, B-tile) intersection pairs for a blocked
    S×S multiply with kb contraction block-columns, tiles uniformly
    scattered: each A tile in contraction column c meets the
    ~nnzb_b/kb B tiles of block-row c."""
    return nnzb_a * (nnzb_b / max(kb, 1))


def spgemm_saved_estimate(nnzb_a: float, nnzb_b: float,
                          kb: int, k: int, m: int, bs: int,
                          itemsize: int = 4) -> dict:
    """Estimated work the SpGEMM dispatch avoids vs the densify
    fallback (SpMM over a DENSIFIED right operand — executor.py's S×S
    fallthrough): FLOPs of 2·nnzb_a·bs²·m against 2·pairs·bs³, and the
    HBM bytes of the dense (k, m) operand that is never materialised.
    Feeds planner.matmul_decisions → obs/ query events."""
    pairs = spgemm_pairs_estimate(nnzb_a, nnzb_b, kb)
    flops_densify = 2.0 * nnzb_a * bs * bs * m
    flops_spgemm = 2.0 * pairs * bs * bs * bs
    return {
        "est_pairs": pairs,
        "est_saved_flops": max(0.0, flops_densify - flops_spgemm),
        "est_saved_hbm_bytes": max(
            0.0, float(k) * m * itemsize - nnzb_b * bs * bs * itemsize),
    }
