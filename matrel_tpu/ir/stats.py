"""Dimension + sparsity statistics propagation (SURVEY.md §2
"Statistics / sparsity estimation").

The reference propagates (nRows, nCols, nnz) bottom-up through the Catalyst
plan and feeds the estimates to the matrix-chain DP and physical strategy
choice. Same role here: pure-Python estimates over the MatExpr tree, no
devices involved.

Estimation model (standard independence assumptions, as in MatFast/MatRel):
  density(A·B)   ≈ 1 - (1 - dA*dB)^k   (k = contraction dim)
  density(A+B)   ≈ min(1, dA + dB)
  density(A⊙B)  ≈ dA * dB
  transpose/scalar-mul preserve density; scalar-add densifies.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple


def density_of(nnz: Optional[int], shape: Tuple[int, int]) -> float:
    if nnz is None:
        return 1.0
    n = shape[0] * shape[1]
    return min(1.0, nnz / n) if n else 0.0


def nnz_from_density(d: float, shape: Tuple[int, int]) -> int:
    return int(round(min(1.0, max(0.0, d)) * shape[0] * shape[1]))


def matmul_density(da: float, db: float, k: int) -> float:
    """Probability an output entry is nonzero given k independent trials."""
    p = da * db
    if p <= 0.0:
        return 0.0
    if p >= 1.0:
        return 1.0
    # 1-(1-p)^k, computed stably.
    return -math.expm1(k * math.log1p(-p))


def add_density(da: float, db: float) -> float:
    return min(1.0, da + db)


def elemmul_density(da: float, db: float) -> float:
    return da * db


def matmul_cost(
    n: int, k: int, m: int, da: float = 1.0, db: float = 1.0
) -> float:
    """Estimated FLOP cost of an (n×k)·(k×m) multiply.

    Sparsity-aware as in the reference's chain DP: work scales with the
    expected number of nonzero multiply-accumulate pairs.
    """
    return 2.0 * n * k * m * da * db


def matmul_out_nnz(
    n: int, k: int, m: int, nnz_a: Optional[int], nnz_b: Optional[int]
) -> Optional[int]:
    if nnz_a is None and nnz_b is None:
        return None
    da = density_of(nnz_a, (n, k))
    db = density_of(nnz_b, (k, m))
    return nnz_from_density(matmul_density(da, db, k), (n, m))
