"""Dimension + sparsity statistics propagation (SURVEY.md §2
"Statistics / sparsity estimation").

The reference propagates (nRows, nCols, nnz) bottom-up through the Catalyst
plan and feeds the estimates to the matrix-chain DP and physical strategy
choice. Same role here: pure-Python estimates over the MatExpr tree, no
devices involved.

Estimation model (standard independence assumptions, as in MatFast/MatRel):
  density(A·B)   ≈ 1 - (1 - dA*dB)^k   (k = contraction dim)
  density(A+B)   ≈ min(1, dA + dB)
  density(A⊙B)  ≈ dA * dB
  transpose/scalar-mul preserve density; scalar-add densifies.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple


def density_of(nnz: Optional[int], shape: Tuple[int, int]) -> float:
    if nnz is None:
        return 1.0
    n = shape[0] * shape[1]
    return min(1.0, nnz / n) if n else 0.0


def nnz_from_density(d: float, shape: Tuple[int, int]) -> int:
    return int(round(min(1.0, max(0.0, d)) * shape[0] * shape[1]))


def matmul_density(da: float, db: float, k: int) -> float:
    """Probability an output entry is nonzero given k independent trials."""
    p = da * db
    if p <= 0.0:
        return 0.0
    if p >= 1.0:
        return 1.0
    # 1-(1-p)^k, computed stably.
    return -math.expm1(k * math.log1p(-p))


def add_density(da: float, db: float) -> float:
    return min(1.0, da + db)


def elemmul_density(da: float, db: float) -> float:
    return da * db


def matmul_cost(
    n: int, k: int, m: int, da: float = 1.0, db: float = 1.0
) -> float:
    """Estimated FLOP cost of an (n×k)·(k×m) multiply.

    Sparsity-aware as in the reference's chain DP: work scales with the
    expected number of nonzero multiply-accumulate pairs.
    """
    return 2.0 * n * k * m * da * db


HBM_FLOPS_PER_BYTE = 120.0
"""Blend factor converting HBM bytes into f32-FLOP-equivalents for the
precision-tier cost model (planner.tier_matmul_cost): a v5e chip
retires ~98e12 f32-class FLOP/s against ~819 GB/s of HBM, so ~120 f32
FLOPs buy the time of one HBM byte. Order-of-magnitude, like
COMM_FLOPS_PER_BYTE below — the term makes bandwidth-bound shapes rank
half-width bf16 operand traffic honestly against pass counts."""


def integral_abs_bound(node, memo: dict = None):
    """Conservative upper bound on max|entry| of a provably-integral
    expression, or None when no bound can be proven. The magnitude
    half of the integer-exactness story: :func:`infer_integral` proves
    entries are integers, this proves HOW BIG — the int-tier chooser
    only auto-picks int32 when the accumulated product
    k·bound(A)·bound(B) provably fits the int32 accumulator, so
    "exact" can never silently wrap (the review-round overflow hole).
    Leaf bounds come from ``BlockMatrix.int_abs_max`` (recorded by
    from_numpy for integral sources); anything unproven is None and
    the chooser conservatively keeps f32. Duck-typed like
    infer_integral; pass a shared ``memo`` to amortise across a
    planning pass."""
    if memo is None:
        memo = {}

    def walk(n):
        key = ("bound", n.uid)
        if key in memo:
            return memo[key]
        memo[key] = got = _bound(n)
        return got

    def _mix(vals, fn):
        if any(v is None for v in vals):
            return None
        return float(fn(vals))

    def _bound(n):
        k = n.kind
        if k in ("leaf", "sparse_leaf", "coo_leaf"):
            v = getattr(n.attrs.get("matrix"), "int_abs_max", None)
            return float(v) if v is not None else None
        if k in ("transpose", "select_index", "select_block", "vec"):
            return walk(n.children[0])
        if k == "select_value":
            return _mix([walk(n.children[0]),
                         abs(float(n.attrs.get("fill", 0.0)))], max)
        if k == "matmul":
            ba, bb = walk(n.children[0]), walk(n.children[1])
            if ba is None or bb is None:
                return None
            return float(n.children[0].shape[1]) * ba * bb
        if k == "elemwise":
            op = n.attrs.get("op")
            vals = [walk(c) for c in n.children]
            if op in ("add", "sub"):
                return _mix(vals, sum)
            if op == "mul":
                return _mix(vals, lambda v: v[0] * v[1])
            if op in ("min", "max"):
                return _mix(vals, max)
            return None
        if k == "scalar":
            op, v = n.attrs["op"], abs(float(n.attrs["value"]))
            b = walk(n.children[0])
            if b is None:
                return None
            if op == "add":
                return b + v
            if op == "mul":
                return b * v
            if op == "pow" and v >= 1:
                return b ** v
            return None
        if k == "agg":
            kind, axis = n.attrs["agg"], n.attrs["axis"]
            c = n.children[0]
            b = walk(c)
            if kind == "count":
                return float(max(c.shape[0] * c.shape[1], 1))
            if b is None:
                return None
            if kind in ("max", "min"):
                return b
            if kind == "sum":
                terms = {"row": c.shape[1], "col": c.shape[0],
                         "all": c.shape[0] * c.shape[1],
                         "diag": min(c.shape)}[axis]
                return float(terms) * b
            return None
        if k == "rank1":
            ba, bu, bv = (walk(c) for c in n.children)
            if None in (ba, bu, bv):
                return None
            return ba + bu * bv
        if k == "join_index":
            mk = n.attrs.get("merge_kind")
            vals = [walk(c) for c in n.children]
            if mk == "add":
                return _mix(vals, sum)
            if mk == "mul":
                return _mix(vals, lambda v: v[0] * v[1])
            if mk in ("left", "right"):
                return _mix(vals, max)
            return None
        return None

    return walk(node)


def infer_integral(node, memo: dict = None) -> bool:
    """Is this expression provably INTEGER-VALUED (every entry an exact
    integer representable in f32)? The static inference that lets an
    "exact" precision SLA route integer-shaped workloads (triangle
    counting, PageRank iteration counts, boolean semiring joins) onto
    the exact int32/int8 MXU tiers instead of conservatively pinning
    f32 (docs/PRECISION.md). Duck-typed over MatExpr (kind/children/
    attrs) — expr.py imports this module, not vice versa.

    Conservative by construction: False whenever exactness cannot be
    proven, so a float workload can never be silently truncated. Leaf
    integrality comes from ``BlockMatrix.integral`` (auto-detected for
    integer/bool numpy sources, or declared by the caller). Pass a
    shared ``memo`` dict to amortise the walk across a planning pass
    (the infer_dtype precedent — per-node fresh memos made deep-chain
    annotation O(nodes²), review r8). The memo is shared with
    :func:`integral_abs_bound` (distinct key spaces)."""
    if memo is None:
        memo = {}

    def walk(n) -> bool:
        key = ("int", n.uid)
        got = memo.get(key)
        if got is None:
            memo[key] = got = _integral(n)
        return got

    def _integral(n) -> bool:
        k = n.kind
        if k in ("leaf", "sparse_leaf", "coo_leaf"):
            return bool(getattr(n.attrs.get("matrix"), "integral",
                                False))
        if k in ("transpose", "select_index", "select_block", "vec"):
            return walk(n.children[0])
        if k == "select_value":
            # non-matching entries become the fill value
            fill = float(n.attrs.get("fill", 0.0))
            return fill.is_integer() and walk(n.children[0])
        if k == "matmul":
            # a bf16-tiered product of integers is NOT integer-valued:
            # the bf16 passes round (the tier is stamped bottom-up
            # before any consumer asks, so the claim is read here)
            if n.attrs.get("precision_tier") in ("bf16x1", "bf16x3"):
                return False
            return all(walk(c) for c in n.children)
        if k == "elemwise":
            if n.attrs.get("op") == "div":
                return False
            return all(walk(c) for c in n.children)
        if k == "scalar":
            op, v = n.attrs["op"], float(n.attrs["value"])
            if op in ("add", "mul"):
                return v.is_integer() and walk(n.children[0])
            if op == "pow":
                return v.is_integer() and v >= 1 and walk(n.children[0])
            return False
        if k == "agg":
            kind = n.attrs["agg"]
            if kind == "count":
                return True          # nonzero counts are integers
            if kind in ("sum", "max", "min"):
                return walk(n.children[0])
            return False             # avg divides
        if k == "rank1":
            return all(walk(c) for c in n.children)
        if k in ("join_index", "join_rows", "join_cols", "join_value"):
            # structured merges are closed over integers; callables are
            # black boxes
            if n.attrs.get("merge_kind") in ("left", "right", "add",
                                             "mul"):
                return all(walk(c) for c in n.children)
            return False
        return False

    return walk(node)


COMM_FLOPS_PER_BYTE = 1000.0
"""Blend factor converting ICI bytes into FLOP-equivalents for the
chain DP's step cost: a v5e chip retires ~200e12 bf16 FLOP/s against
~200 GB/s of per-link ICI, so ~1000 MXU FLOPs buy the time of one
ICI byte. Order-of-magnitude is what matters — the term breaks
FLOP-ties toward the cheaper collective bill."""


#: Layout codes shared with native/chain_dp.cc's layout-aware DP — the
#: C side receives operand layouts as int8 with exactly this mapping.
LAYOUT_CODES = {"2d": 0, "row": 1, "col": 2, "rep": 3, "other": 4}


def comm_proxy_layout(n: int, k: int, m: int, da: float, db: float,
                      gx: int, gy: int, itemsize: int = 4,
                      la: str = "2d", lb: str = "2d",
                      weights: tuple = (1.0, 1.0)
                      ) -> tuple:
    """(cheapest per-device ICI cost, output layout of the argmin
    strategy) for an (n×k)·(k×m) multiply on a gx×gy mesh — the chain
    DP's comm term, PER-LAYOUT (round 5) and now TOPOLOGY-WEIGHTED
    (round 7: ``weights`` are the per-axis inverse-bandwidth weights of
    core/mesh.MeshTopology, so the DP ranks parenthesisations by what
    their collectives cost on a hierarchical ICI/DCN mesh, not by flat
    bytes).

    Delegates to planner.comm_cost per strategy (ONE Python source of
    truth for the per-layout closed forms — review r5; the only copy is
    the C mirror in native/chain_dp.cc, equivalence-fuzzed by
    test_native) but still applies NO admissibility or broadcast-
    threshold gates (the planner picks the real strategy per multiply
    afterwards). Tie-break order (bmm_right, bmm_left, cpmm, rmm) MUST
    stay in sync with native/chain_dp.cc's comm_proxy_layout."""
    p = gx * gy
    if p <= 1:
        return 0.0, "2d"
    from matrel_tpu.parallel import planner   # lazy: no import cycle
    best, lay = None, "2d"
    for strat, out_lay in (("bmm_right", "row"), ("bmm_left", "col"),
                           ("cpmm", "2d"), ("rmm", "2d")):
        c = planner.comm_cost(strat, n, k, m, da, db, gx, gy,
                              itemsize, la, lb, weights=weights)
        if best is None or c < best:
            best, lay = c, out_lay
    return best, lay


def comm_proxy(n: int, k: int, m: int, da: float, db: float,
               gx: int, gy: int, itemsize: int = 4) -> float:
    """comm_proxy_layout at the canonical "2d" layouts — the
    layout-blind view kept for callers that predate the layout-aware
    DP (and for the native matrel_chain_dp_comm symbol's semantics)."""
    return comm_proxy_layout(n, k, m, da, db, gx, gy, itemsize)[0]


def chain_step_cost(n: int, k: int, m: int, da: float, db: float,
                    gx: int = 1, gy: int = 1) -> float:
    """DP step cost: sparsity-aware FLOPs + the collective bill in
    FLOP-equivalents. With gx·gy == 1 this is exactly matmul_cost, so
    single-device plans are unchanged."""
    return (matmul_cost(n, k, m, da, db)
            + COMM_FLOPS_PER_BYTE * comm_proxy(n, k, m, da, db, gx, gy))


def chain_step_cost_layout(n: int, k: int, m: int, da: float, db: float,
                           gx: int, gy: int, la: str, lb: str,
                           weights: tuple = (1.0, 1.0),
                           flop_scale: float = 1.0,
                           comm_weight=None) -> tuple:
    """(step cost, output layout): chain_step_cost with per-layout,
    topology-weighted comm terms — the layout-aware DP's step (round 5;
    weights round 7). ``flop_scale`` (round 8) is the precision tier's
    relative MXU time per MAC (planner.sla_compute_factor): a "fast"
    bf16 query retires its FLOPs faster, so the comm term weighs
    relatively MORE and the DP may legitimately prefer a different
    parenthesisation. 1.0 (the default, and every "default"-SLA query)
    is bit-identical to the pre-tier step cost.

    ``comm_weight`` overrides :data:`COMM_FLOPS_PER_BYTE` with a
    MEASURED flops-per-byte conversion for this step's shape class
    (parallel/coeffs.chain_comm_weights — the drift-calibrated ratio
    of interconnect time to MXU time on the live backend, consulted
    under ``config.coeff_planner_enable``; docs/COST_MODEL.md). None
    (the default, and every cold class) keeps the analytic constant —
    bit-identical."""
    comm, lay = comm_proxy_layout(n, k, m, da, db, gx, gy, la=la, lb=lb,
                                  weights=weights)
    w = COMM_FLOPS_PER_BYTE if comm_weight is None else float(comm_weight)
    return (matmul_cost(n, k, m, da, db) * flop_scale
            + w * comm), lay


def matmul_out_nnz(
    n: int, k: int, m: int, nnz_a: Optional[int], nnz_b: Optional[int]
) -> Optional[int]:
    if nnz_a is None and nnz_b is None:
        return None
    da = density_of(nnz_a, (n, k))
    db = density_of(nnz_b, (k, m))
    return nnz_from_density(matmul_density(da, db, k), (n, m))


# -- sparsity-structure classification (ops/kernel_registry.py) -------------
# The structure-specialized SpGEMM kernels (JITSPMM's thesis,
# arXiv:2312.05639) need to KNOW the shape of the sparsity, not just
# its density. These closed-form classifiers read the block edge lists
# the engine already computes (BlockSparseMatrix.block_rows/cols; COO
# leaves bucketed at the dispatch block size) and bin each operand into
# one of the STRUCTURE_CLASSES. Host-only numpy, no devices — the same
# contract as everything else in this module.


#: Structure-class vocabulary, most-specific first. "generic" is the
#: conservative fallback every boundary case must land in.
STRUCTURE_CLASSES = ("row_band", "clustered_tile", "powerlaw_coo",
                     "generic")

#: row_band: p90 of |tile offset (col - row) - median offset| must sit
#: inside this fraction of the grid (or within BAND_SPREAD_TILES tiles
#: absolutely — a tridiagonal or 5-point stencil band qualifies on any
#: grid size).
BAND_SPREAD_FRAC = 0.08
BAND_SPREAD_TILES = 2.0

#: powerlaw_coo: max per-block-row tile count >= this multiple of the
#: MEDIAN (over OCCUPIED rows — the median is hub-robust where the
#: mean is not: on a small grid two hub rows lift the mean enough to
#: hide themselves), with at least POWERLAW_MIN_ROWS occupied rows so
#: a 2-row matrix can't fake a hub.
POWERLAW_SKEW = 6.0
POWERLAW_MIN_ROWS = 8

#: clustered_tile: mean occupied-4-neighbor count must beat the
#: uniform-random expectation (4 * block density) by this factor AND
#: clear an absolute floor; above CLUSTER_MAX_DENSITY everything is
#: neighborly and the class says nothing.
CLUSTER_NEIGHBOR_LIFT = 3.0
CLUSTER_NEIGHBOR_MIN = 1.0
CLUSTER_MAX_DENSITY = 0.5

#: Below this many tiles no classifier has evidence — generic.
STRUCTURE_MIN_TILES = 4


def classify_block_structure(rows, cols, gr: int, gc: int) -> str:
    """Structure class of one sparse operand from its block edge lists.

    ``rows``/``cols`` are the tile coordinates (int arrays, any order,
    duplicates allowed) on a (gr, gc) tile grid. Checks most-specific
    first — row_band, then powerlaw_coo, then clustered_tile — and
    falls back to "generic" whenever the evidence is thin (fewer than
    STRUCTURE_MIN_TILES tiles, degenerate grids, boundary histograms
    that clear no threshold)."""
    import numpy as np
    rows = np.asarray(rows, np.int64).ravel()
    cols = np.asarray(cols, np.int64).ravel()
    if rows.size < STRUCTURE_MIN_TILES or gr < 2 or gc < 2:
        return "generic"
    if rows.size != cols.size:
        return "generic"
    ntiles = len(np.unique(rows * gc + cols))
    density = ntiles / float(gr * gc)

    # row_band: tiles hug one (possibly shifted) diagonal — the TILE
    # offset col - row concentrates around its median. Measured in
    # tiles: the absolute floor admits stencil-width bands on any
    # grid, the fractional term scales with flagship grids.
    off = (cols - rows).astype(np.float64)
    med = float(np.median(off))
    dev = float(np.quantile(np.abs(off - med), 0.90))
    if dev <= max(BAND_SPREAD_TILES, BAND_SPREAD_FRAC * min(gr, gc)):
        return "row_band"

    # powerlaw_coo: per-block-row tile counts skewed (the PageRank /
    # hub-graph shape) — a few rows own most of the tiles.
    occ = np.bincount(rows, minlength=gr)
    occ = occ[occ > 0]
    if (occ.size >= POWERLAW_MIN_ROWS
            and float(occ.max())
            >= POWERLAW_SKEW * float(np.median(occ))):
        return "powerlaw_coo"

    # clustered_tile: occupied tiles form dense blobs — the mean count
    # of occupied 4-neighbors beats the uniform-random expectation.
    # Vectorized (sorted-key membership): a million-tile coo_leaf is
    # classified in numpy time, not a Python per-tile loop.
    if density <= CLUSTER_MAX_DENSITY:
        keys = np.unique(rows * gc + cols)
        col = keys % gc
        neigh = (
            (np.isin(keys + 1, keys) & (col < gc - 1)).sum()
            + (np.isin(keys - 1, keys) & (col > 0)).sum()
            + np.isin(keys + gc, keys).sum()
            + np.isin(keys - gc, keys).sum())
        mean_neigh = float(neigh) / max(keys.size, 1)
        if (mean_neigh >= CLUSTER_NEIGHBOR_MIN
                and mean_neigh >= CLUSTER_NEIGHBOR_LIFT * 4.0 * density):
            return "clustered_tile"
    return "generic"


def pair_structure_class(class_a: str, class_b: str) -> str:
    """Structure class of an S×S operand PAIR — what the SpGEMM kernel
    actually runs over. Conservative: a specialized kernel is only
    nominated when BOTH operands share its home structure (A·A-shaped
    graph workloads, band×band chains); any mix falls back to
    "generic", where the legacy kernels stand."""
    if class_a == class_b and class_a in STRUCTURE_CLASSES:
        return class_a
    return "generic"


# -- block-granular SpGEMM estimates (ops/spgemm.py dispatch + pricing) -----


def block_density(elem_density: float, block_size: int) -> float:
    """Probability a block_size×block_size tile holds ≥1 nonzero, under
    the same independence assumption as matmul_density — lifts an
    ELEMENT density (COO leaves) to the BLOCK granularity the SpGEMM
    tile-intersection reasons at. Same stable 1-(1-p)^k form."""
    if elem_density <= 0.0:
        return 0.0
    if elem_density >= 1.0:
        return 1.0
    return -math.expm1(block_size * block_size
                       * math.log1p(-elem_density))


def spgemm_pairs_estimate(nnzb_a: float, nnzb_b: float, kb: int) -> float:
    """Expected (A-tile, B-tile) intersection pairs for a blocked
    S×S multiply with kb contraction block-columns, tiles uniformly
    scattered: each A tile in contraction column c meets the
    ~nnzb_b/kb B tiles of block-row c."""
    return nnzb_a * (nnzb_b / max(kb, 1))


def spgemm_saved_estimate(nnzb_a: float, nnzb_b: float,
                          kb: int, k: int, m: int, bs: int,
                          itemsize: int = 4) -> dict:
    """Estimated work the SpGEMM dispatch avoids vs the densify
    fallback (SpMM over a DENSIFIED right operand — executor.py's S×S
    fallthrough): FLOPs of 2·nnzb_a·bs²·m against 2·pairs·bs³, and the
    HBM bytes of the dense (k, m) operand that is never materialised.
    Feeds planner.matmul_decisions → obs/ query events."""
    pairs = spgemm_pairs_estimate(nnzb_a, nnzb_b, kb)
    flops_densify = 2.0 * nnzb_a * bs * bs * m
    flops_spgemm = 2.0 * pairs * bs * bs * bs
    return {
        "est_pairs": pairs,
        "est_saved_flops": max(0.0, flops_densify - flops_spgemm),
        "est_saved_hbm_bytes": max(
            0.0, float(k) * m * itemsize - nnzb_b * bs * bs * itemsize),
    }
