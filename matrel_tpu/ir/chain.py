"""Matrix-chain multiplication reordering — MatRel's flagship optimization
(SURVEY.md §2 "Optimizer: matrix-chain DP", §3.3).

"The join-order optimizer of linear algebra": collect maximal chains of
matmul nodes A·B·C·…, run the classic O(n³) interval DP with a
dimension- AND sparsity-aware cost model, and re-parenthesise the tree to
the minimum-cost order. Pure Python, runs before tracing; unit-testable
without devices (SURVEY.md §4).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from matrel_tpu.ir import stats
from matrel_tpu.ir.expr import MatExpr, matmul


def collect_chain(e: MatExpr) -> List[MatExpr]:
    """Flatten a maximal matmul tree into its ordered operand list."""
    if e.kind != "matmul":
        return [e]
    return collect_chain(e.children[0]) + collect_chain(e.children[1])


def _operand_layouts(operands: List[MatExpr], mesh,
                     config=None) -> List[str]:
    """Layout of each chain operand on the mesh (planner.infer_layout
    under the SESSION config — its COO claim is config-dependent), or
    all-"2d" when no mesh is given (the layout-blind DP)."""
    if mesh is None:
        return ["2d"] * len(operands)
    from matrel_tpu.parallel import planner   # lazy: no import cycle
    memo: dict = {}
    return [planner.infer_layout(op, mesh, memo, config)
            for op in operands]


def optimal_order(operands: List[MatExpr],
                  grid: Tuple[int, int] = (1, 1),
                  mesh=None, config=None) -> Tuple[MatExpr, float]:
    """Interval DP over the operand list; returns (rebuilt expr, est. cost).

    cost[i][j] = min over split s of cost[i][s] + cost[s+1][j]
                 + stepCost(dims, densities, layouts, grid)
    stepCost (stats.chain_step_cost_layout) = sparsity-aware FLOPs + the
    collective bill of the cheapest MM strategy on the grid in
    FLOP-equivalents — two parenthesisations with equal FLOPs but
    different comm bills no longer tie arbitrarily, and with ``mesh``
    given the bill is PER-LAYOUT (round 5): a replicated or 1D-sharded
    operand makes the order that broadcasts it free strictly cheaper,
    and each interval's result carries the layout its cheapest strategy
    would emit. grid == (1, 1) reduces to pure FLOPs. Densities of
    intermediates are re-estimated per split via the same propagation
    the stats module uses, so sparse chains order correctly.

    For chains of ≥3 operands the O(n³) loop runs in the native optimizer
    core (native/chain_dp.cc, same cost semantics incl. the layout-aware
    comm term); the pure-Python DP below is the always-available fallback
    and the reference implementation for equivalence tests.
    """
    n = len(operands)
    gx, gy = grid
    if n == 1:
        return operands[0], 0.0
    lays = _operand_layouts(operands, mesh if gx * gy > 1 else None,
                            config)
    # topology weights (core/mesh.MeshTopology): with a mesh in hand the
    # DP's comm term bills each strategy's legs per axis, so the order
    # that keeps traffic off a slow DCN axis wins; grid-only callers
    # (and single-device grids) stay on the flat model
    weights = (1.0, 1.0)
    if mesh is not None and gx * gy > 1:
        from matrel_tpu.core import mesh as mesh_lib
        weights = mesh_lib.axis_weights(mesh, config)
    # precision tier (round 8): under a non-default SLA the query's
    # MACs retire at the tier's MXU rate, so the comm term weighs
    # relatively more — the DP's FLOP side scales by the tier factor
    # (planner.sla_compute_factor; 1.0 under "default", bit-identical).
    # The native DP mirror predates tiers, so scaled requests run the
    # Python DP — degrade to the reference implementation, never to
    # dishonest pricing (the weighted-topology precedent).
    from matrel_tpu.parallel import planner as _planner   # lazy: no cycle
    flop_scale = _planner.sla_compute_factor(config)
    # staged reshard pricing (round 10): with reshard_peak_budget_bytes
    # set, the planner prices opposite-1D re-lays from the compiled
    # ReshardPlan — which a tight budget forces onto the higher staged
    # bill the native mirror's closed forms do not know. Degrade to the
    # Python DP (the reference implementation) rather than misprice —
    # the flop_scale/topology precedent; the equivalence fuzz
    # cross-checks native vs the plan-derived costs at budget 0, where
    # the two are bit-identical by construction (tests/test_reshard.py).
    reshard_budget = getattr(config, "reshard_peak_budget_bytes", 0) \
        if config is not None else 0
    # learned comm weights (round 19, parallel/coeffs.py — the ML018
    # seam; docs/COST_MODEL.md): under coeff_planner_enable each DP
    # step's byte bill converts to FLOP-equivalents at the MEASURED
    # flops-per-byte ratio of its shape class on the live backend,
    # instead of the analytic COMM_FLOPS_PER_BYTE constant. Cold
    # classes keep the constant. The native mirror predates learned
    # weights, so coefficient-active requests run the Python DP —
    # degrade to the reference implementation, never to dishonest
    # pricing (the flop_scale/reshard-budget precedent).
    coeff_cw = None
    shape_cls = None
    if (config is not None
            and getattr(config, "coeff_planner_enable", False)
            and gx * gy > 1):
        from matrel_tpu.parallel import coeffs as coeffs_lib
        from matrel_tpu.obs import drift as drift_lib
        import jax
        coeff_cw = coeffs_lib.chain_comm_weights(
            drift_lib.table_path(config), jax.default_backend(),
            min_samples=getattr(config, "coeff_min_samples", 1)) or None
        if coeff_cw is not None:
            shape_cls = drift_lib.shape_class
    if (n >= 3 and flop_scale == 1.0 and reshard_budget == 0
            and coeff_cw is None):
        from matrel_tpu.utils import native
        dims = [op.shape[0] for op in operands] + [operands[-1].shape[1]]
        dens = [op.density for op in operands]
        codes = [stats.LAYOUT_CODES[l] for l in lays]
        res = native.chain_dp(dims, dens, grid=grid, layouts=codes,
                              weights=weights)
        if res is not None:
            splits, cost = res

            def build(i: int, j: int) -> MatExpr:
                if i == j:
                    return operands[i]
                s = int(splits[i][j])
                return matmul(build(i, s), build(s + 1, j))

            return build(0, n - 1), cost
    # best[i][j] = (cost, expr, layout) for operands[i..j] inclusive
    best: List[List[Optional[Tuple[float, MatExpr, str]]]] = [
        [None] * n for _ in range(n)
    ]
    for i in range(n):
        best[i][i] = (0.0, operands[i], lays[i])
    for span in range(2, n + 1):
        for i in range(0, n - span + 1):
            j = i + span - 1
            cand: Optional[Tuple[float, MatExpr, str]] = None
            for s in range(i, j):
                cl, el, ll = best[i][s]
                cr, er, lr = best[s + 1][j]
                cw = (coeff_cw.get(shape_cls(
                    (el.shape[0], el.shape[1], er.shape[1])))
                    if coeff_cw is not None else None)
                step, lay = stats.chain_step_cost_layout(
                    el.shape[0], el.shape[1], er.shape[1],
                    el.density, er.density, gx, gy, ll, lr,
                    weights=weights, flop_scale=flop_scale,
                    comm_weight=cw,
                )
                total = cl + cr + step
                if cand is None or total < cand[0]:
                    cand = (total, matmul(el, er), lay)
            best[i][j] = cand
    cost, e, _ = best[0][n - 1]
    return e, cost


def reorder_chains(e: MatExpr,
                   grid: Tuple[int, int] = (1, 1),
                   mesh=None, config=None) -> MatExpr:
    """Recursively find maximal matmul chains and DP-reorder each.
    ``grid`` is the mesh grid shape feeding the comm-aware step cost;
    ``mesh`` additionally makes the step cost layout-aware (the DP sees
    which operands are replicated/1D-sharded on it), under the session
    ``config`` the planner will also use."""
    if e.kind == "matmul":
        ops = collect_chain(e)
        # optimize below each chain operand first, then the chain itself
        ops = [reorder_chains(o, grid, mesh, config)
               if o.kind != "leaf" else o for o in ops]
        if len(ops) > 2:
            new, _ = optimal_order(ops, grid, mesh, config)
            return new
        if len(ops) == 2:
            return matmul(ops[0], ops[1])
        return ops[0]
    if not e.children:
        return e
    new_children = tuple(
        reorder_chains(c, grid, mesh, config) for c in e.children
    )
    if all(nc is oc for nc, oc in zip(new_children, e.children)):
        return e
    return e.with_children(new_children)


def chain_cost(e: MatExpr, grid: Tuple[int, int] = (1, 1)) -> float:
    """Total estimated matmul cost of a (sub)tree, for plan assertions.
    Pure FLOPs at the default grid; comm-aware otherwise."""
    total = 0.0
    if e.kind == "matmul":
        l, r = e.children
        total += stats.chain_step_cost(
            l.shape[0], l.shape[1], r.shape[1], l.density, r.density,
            grid[0], grid[1],
        )
    for c in e.children:
        total += chain_cost(c, grid)
    return total
