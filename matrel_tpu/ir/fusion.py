"""Whole-plan program fusion — region segmentation over annotated plans.

At 184 TFLOPS/chip the matmuls are near peak; the remaining wall-clock
is *between* ops — every elementwise/aggregation/scalar step lowers
through its own dispatch at the executor's one ``annotate()`` site,
paying a dispatch and an HBM round-trip per plan edge (ROADMAP item 3).
This module is the PLANNER half of the fix (the MatFast/Catalyst fusion
thesis, PAPER.md [P2], done at the XLA level; JITSPMM's
generate-code-for-the-observed-workload argument, arXiv:2312.05639,
applied one level up): segment the annotated plan into FUSABLE REGIONS
— connected subgraphs of elementwise chains, scalar ops and reductions,
each optionally anchored on ONE producer matmul/SpGEMM whose epilogue
the region becomes — and stamp each region on its root node so that

* the executor (``executor.Lowerer``) lowers the whole region under ONE
  ``annotate()`` dispatch frame, with the epilogue chain absorbed into
  the producing contraction through the kernels' epilogue slots
  (``ops/kernel_registry.py`` / ``ops/spmm.py`` /
  ``parallel/strategies.py``),
* the region-program seam (``executor.compile_region_units``) can emit
  one jitted program per region — XLA sees the whole segment instead of
  per-op dispatches (``compile_staged_units`` is the per-op floor the
  fused form is measured against),
* ``planner.matmul_decisions`` records the chosen boundary
  (``fused_region``, member census, ``est_saved_dispatches`` /
  ``est_saved_hbm_bytes``) into the obs event stream, and
* MV111 (``analysis/fusion_pass.py``) re-derives every boundary and
  verifies each stamp covers exactly the region the executor lowers.

Fusion boundaries are planner decisions: with ``config.autotune`` on,
``parallel/autotune.lookup_or_measure_fusion`` measures fused-vs-staged
per region shape class (persisted under the ``fuse|…`` key family) and
a measured "staged" winner suppresses the stamp.

``config.fusion_enable`` (default False) gates EVERYTHING here: off,
``segment`` returns ``[]`` without constructing a single
:class:`FusedRegion` (``_CONSTRUCTED`` is the test hook pinning that),
``annotate_fusion`` returns the tree untouched, and the engine is
bit-identical to the per-op path (plan snapshots unchanged).

Region grammar (docs/FUSION.md):

* FUSABLE kinds: ``elemwise``, ``scalar``, ``agg``, ``select_value``,
  ``select_index`` — the zero-padding-aware pointwise/reduction
  lowerings. Layout ops (``transpose``, ``vec``), joins and solves are
  boundaries.
* A region ROOT is a fusable node that no fusable parent absorbs
  (parent not fusable, or the node has ≠ 1 consumers).
* A member absorbs a CHILD when the child is fusable and has exactly
  one consumer in the plan (shared DAG nodes are boundaries — their
  value is memoised once by the executor, so fusing them into one
  consumer would recompute them for the others).
* At most ONE matmul anchor per region: a single-consumer matmul child
  of a member is absorbed as the region's producer; the member chain
  ABOVE it becomes the kernel epilogue, fusable single-consumer
  children BELOW it (operand prologues, e.g. PageRank's ``w·r``) join
  the region program. Nothing is absorbed past a second matmul.
* A region needs ≥ 2 members — a lone fusable op has nothing to fuse.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from matrel_tpu.config import MatrelConfig, default_config
from matrel_tpu.ir.expr import MatExpr

#: Node kinds a region may absorb as members.
FUSABLE_KINDS = ("elemwise", "scalar", "agg", "select_value",
                 "select_index")

#: Node kinds that may anchor a region as its producer contraction.
ANCHOR_KINDS = ("matmul",)

#: Test/obs hook: how many FusedRegion objects were ever constructed.
#: The bit-identity contract says ZERO with ``fusion_enable`` off —
#: the default compile path must not even build region objects
#: (the kernel_registry._LOOKUPS idiom; test-enforced).
_CONSTRUCTED = {"count": 0}


@dataclasses.dataclass(frozen=True)
class FusedRegion:
    """One fusable region of an annotated plan.

    ``root_uid`` is the region's output node; ``member_uids`` every
    member EXCLUDING the root (the root's own uid changes when the
    stamp is applied, so it is implicit); ``anchor_uid`` the producer
    matmul absorbed into the region (or None for matmul-free
    elementwise/reduction chains). ``sig`` is the canonical census
    signature used by autotune ``fuse|`` keys and the drift auditor's
    ``fused:<sig>`` calibration rows — '|'-free by construction (it
    embeds in '|'-separated table keys)."""

    root_uid: int
    member_uids: Tuple[int, ...]
    anchor_uid: Optional[int]
    sig: str
    census: Dict[str, int]
    n_remask: int
    saved_dispatches: int
    saved_hbm_bytes: float

    def __post_init__(self):
        _CONSTRUCTED["count"] += 1


def op_label(n: MatExpr) -> str:
    """Census label for one member: the kind, qualified by the
    sub-operation where one kind covers several (``elemwise.mul``,
    ``scalar.add``, ``agg.sum``; ``mm`` for the anchor)."""
    if n.kind == "matmul":
        return "mm"
    if n.kind == "elemwise":
        return f"elemwise.{n.attrs['op']}"
    if n.kind == "scalar":
        return f"scalar.{n.attrs['op']}"
    if n.kind == "agg":
        return f"agg.{n.attrs['agg']}"
    return n.kind


def region_sig(census: Dict[str, int]) -> str:
    """Canonical '|'-free signature of a census (sorted, stable across
    sessions — the autotune key / drift row identity)."""
    return "+".join(f"{k}x{v}" for k, v in sorted(census.items()))


def _fusable(n: MatExpr) -> bool:
    return n.kind in FUSABLE_KINDS


def remasks_padding(n: MatExpr) -> bool:
    """Does this member's lowering RE-MASK the zero-padding invariant
    (the executor's ``_mask_to_logical`` breakers — the
    ``padding_pass.PADDING_CONTRACT`` classes)? MV111 compares the
    stamped census of these against its own re-derivation: a fused
    region must restore the invariant exactly where the staged path
    would."""
    if n.kind == "scalar":
        op, v = n.attrs["op"], n.attrs["value"]
        return (op == "add" and v != 0.0) or (op == "pow" and v <= 0)
    if n.kind == "elemwise":
        if n.attrs["op"] == "div":
            return True
        broadcast = n.children[0].shape != n.children[1].shape
        return broadcast and n.attrs["op"] != "mul"
    if n.kind == "select_value":
        return n.attrs["fill"] != 0.0
    if n.kind == "agg":
        return True          # aggregates mask the padded region
    return False


def consumer_counts(roots) -> Dict[int, int]:
    """uid -> number of consuming edges across every root tree (each
    plan output counts as one consumer of its root). Shared DAG nodes
    (count > 1) are region boundaries."""
    counts: Dict[int, int] = {}
    seen: set = set()

    def walk(n: MatExpr):
        if n.uid in seen:
            return
        seen.add(n.uid)
        for c in n.children:
            counts[c.uid] = counts.get(c.uid, 0) + 1
            walk(c)

    for r in roots:
        counts[r.uid] = counts.get(r.uid, 0) + 1
        walk(r)
    return counts


def _is_region_root(n: MatExpr, counts: Dict[int, int],
                    parent_kinds: Dict[int, List[str]]) -> bool:
    """A fusable node roots a region unless exactly one fusable parent
    will absorb it (single consumer + fusable parent)."""
    if not _fusable(n):
        return False
    if counts.get(n.uid, 0) != 1:
        return True
    pk = parent_kinds.get(n.uid) or []
    return not (len(pk) == 1 and pk[0] in FUSABLE_KINDS)


def _gather(root: MatExpr, counts: Dict[int, int]):
    """(members incl. root, anchor or None) for the region rooted at
    ``root`` — the ONE derivation shared by the executor's lowering,
    the unit-program seam and MV111 (the _spgemm_dispatch contract)."""
    members: Dict[int, MatExpr] = {root.uid: root}
    anchor: Optional[MatExpr] = None
    stack = [root]
    while stack:
        n = stack.pop()
        for c in n.children:
            if c.uid in members:
                continue
            if _fusable(c) and counts.get(c.uid, 0) == 1:
                members[c.uid] = c
                stack.append(c)
            elif (c.kind in ANCHOR_KINDS and anchor is None
                    and counts.get(c.uid, 0) == 1):
                anchor = c
                members[c.uid] = c
                stack.append(c)      # operand prologues may join too
    return members, anchor


def segment(root: MatExpr, config: Optional[MatrelConfig] = None,
            mesh=None) -> List[FusedRegion]:
    """The fusable regions of ONE annotated root, in deterministic
    (post-order) root order. ``[]`` — and zero FusedRegion
    constructions — when ``config.fusion_enable`` is off."""
    cfg = config or default_config()
    if not cfg.fusion_enable:
        return []
    counts = consumer_counts((root,))
    parent_kinds: Dict[int, List[str]] = {}
    order: List[MatExpr] = []
    seen: set = set()

    def walk(n: MatExpr):
        if n.uid in seen:
            return
        seen.add(n.uid)
        for c in n.children:
            parent_kinds.setdefault(c.uid, []).append(n.kind)
            walk(c)
        order.append(n)

    walk(root)
    regions: List[FusedRegion] = []
    claimed: set = set()
    # root-most first: a nested fusable root inside another region's
    # member set can only arise via sharing, which _gather refuses, but
    # claim tracking keeps the regions provably disjoint regardless
    for n in reversed(order):
        if n.uid in claimed or not _is_region_root(n, counts,
                                                   parent_kinds):
            continue
        members, anchor = _gather(n, counts)
        if len(members) < 2:
            continue
        if any(u in claimed for u in members):
            continue
        claimed.update(members)
        census: Dict[str, int] = {}
        n_remask = 0
        saved_bytes = 0.0
        for m in members.values():
            lbl = op_label(m)
            census[lbl] = census.get(lbl, 0) + 1
            if remasks_padding(m):
                n_remask += 1
            if m.uid != n.uid and mesh is not None:
                # each absorbed member's intermediate no longer makes
                # an HBM round-trip: one write + one read of its
                # padded f32 array
                from matrel_tpu.core import padding
                pn, pm = padding.padded_shape(m.shape, mesh)
                saved_bytes += 2.0 * pn * pm * 4
        regions.append(FusedRegion(
            root_uid=n.uid,
            member_uids=tuple(sorted(u for u in members
                                     if u != n.uid)),
            anchor_uid=anchor.uid if anchor is not None else None,
            sig=region_sig(census),
            census=census,
            n_remask=n_remask,
            saved_dispatches=len(members) - 1,
            saved_hbm_bytes=saved_bytes,
        ))
    return regions


def annotate_fusion(root: MatExpr, mesh,
                    config: Optional[MatrelConfig] = None) -> MatExpr:
    """Stamp every fusable region on its root node (``fused_region``,
    ``fused_members``, ``fused_anchor``, ``fused_census``,
    ``fused_tier``, ``fused_remask``, ``fused_saved_dispatches``,
    ``fused_saved_hbm_bytes``) — run AFTER ``annotate_strategies`` so
    anchors already carry their strategy/tier stamps, and BEFORE the
    verifier so MV111 sees the boundary. Identity (the same tree
    object) when fusion is off or nothing fuses.

    With ``config.autotune`` on, the boundary is a MEASURED decision:
    a ``fuse|<sig>|…`` table row whose winner is "staged" suppresses
    the stamp (the lookup_or_measure contract — the closed loop
    overrules the model)."""
    cfg = config or default_config()
    if not cfg.fusion_enable:
        return root
    regions = segment(root, cfg, mesh=mesh)
    if not regions:
        return root
    if cfg.autotune:
        from matrel_tpu.parallel import autotune
        kept = []
        for r in regions:
            best = autotune.lookup_or_measure_fusion(r, root, mesh, cfg)
            if best != "staged":
                kept.append(r)
        regions = kept
        if not regions:
            return root
    by_root = {r.root_uid: r for r in regions}
    uidmap: Dict[int, int] = {}
    memo: Dict[int, MatExpr] = {}

    def rebuild(n: MatExpr) -> MatExpr:
        if n.uid in memo:
            return memo[n.uid]
        new_children = tuple(rebuild(c) for c in n.children)
        out = n
        if any(nc is not oc for nc, oc in zip(new_children, n.children)):
            out = n.with_children(new_children)
        r = by_root.get(n.uid)
        if r is not None:
            tier = None
            if r.anchor_uid is not None:
                anchor = _find_uid(n, r.anchor_uid)
                if anchor is not None:
                    tier = anchor.attrs.get("precision_tier")
            out = out.with_attrs(
                fused_region=r.sig,
                # member uids remapped through any nested restamp (a
                # region root BELOW one of this region's members gets
                # a fresh uid when its own stamp lands)
                fused_members=tuple(sorted(uidmap.get(u, u)
                                           for u in r.member_uids)),
                fused_anchor=uidmap.get(r.anchor_uid, r.anchor_uid),
                fused_census=dict(r.census),
                fused_tier=tier,
                fused_remask=r.n_remask,
                fused_saved_dispatches=r.saved_dispatches,
                fused_saved_hbm_bytes=r.saved_hbm_bytes,
            )
        if out is not n:
            uidmap[n.uid] = out.uid
        memo[n.uid] = out
        return out

    return rebuild(root)


def _find_uid(root: MatExpr, uid: int) -> Optional[MatExpr]:
    stack = [root]
    seen: set = set()
    while stack:
        n = stack.pop()
        if n.uid == uid:
            return n
        if n.uid in seen:
            continue
        seen.add(n.uid)
        stack.extend(n.children)
    return None


def region_nodes(root: MatExpr) -> Dict[int, MatExpr]:
    """uid -> node for a stamped region root's member set (root
    included) — the executor's region evaluator and MV111 both read
    the stamp through this one resolver."""
    member_uids = set(root.attrs.get("fused_members") or ())
    out = {root.uid: root}
    stack = [root]
    while stack:
        n = stack.pop()
        for c in n.children:
            if c.uid in member_uids and c.uid not in out:
                out[c.uid] = c
                stack.append(c)
    return out


def collect_stamps(root: MatExpr) -> List[MatExpr]:
    """Every node carrying a ``fused_region`` stamp under ``root``
    (dedup by uid, post-order)."""
    out: List[MatExpr] = []
    seen: set = set()

    def walk(n: MatExpr):
        if n.uid in seen:
            return
        seen.add(n.uid)
        for c in n.children:
            walk(c)
        if "fused_region" in n.attrs:
            out.append(n)

    walk(root)
    return out


def epilogue_elementwise_chain(root: MatExpr, members: Dict[int, MatExpr],
                               anchor_uid: int) -> bool:
    """Is the member chain ABOVE the anchor exclusively zero-preserving,
    shape-polymorphic pointwise ops (scalar mul / pow>0)? Then the
    kernel epilogue hook may apply it TILE-WISE (before the SpGEMM
    scatter — nnzb·bs² elements instead of n·m); anything else takes
    the dense post-scatter application (``kernel_registry``'s
    "dense" epilogue mode)."""
    on_chain: set = set()

    def walk(n: MatExpr) -> bool:
        """True when ``anchor_uid`` is reachable from n through
        members; collect the nodes on such paths."""
        if n.uid == anchor_uid:
            return True
        if n.uid not in members:
            return False
        hit = False
        for c in n.children:
            if walk(c):
                hit = True
        if hit:
            on_chain.add(n.uid)
        return hit

    walk(root)
    for uid in on_chain:
        m = members[uid]
        if m.kind != "scalar":
            return False
        op, v = m.attrs["op"], m.attrs["value"]
        if not (op == "mul" or (op == "pow" and v > 0)):
            return False
    return True
