"""Lazy matrix expression IR — the TPU-native analogue of MatRel's Catalyst
logical plan (SURVEY.md §2 "Logical operators", §3.2).

In the reference every DSL call (``Dataset.multiply``, ``.t()``, ``rowSum()``
…) constructs a Catalyst ``LogicalPlan`` node; nothing executes until an
action triggers analyze → optimize → plan → RDD execution. Here every DSL
call constructs a ``MatExpr`` node; ``.compute()`` triggers
rewrite → chain-DP → physical planning → one jitted XLA program.

Node set mirrors the reference's logical operators:
  Leaf, Transpose, MatMul, Add/Sub/ElemMul/ElemDiv (elementwise),
  ScalarOp (add/mul/pow by a scalar), Agg (sum/count/avg/max/min over
  row/col/all/diag — covers rowSum/colSum/sum/trace), Vec, RankOneUpdate,
  Inverse/Solve (dense local linear solves — the normal-equations step),
  SelectValue/SelectIndex (relational σ), JoinOnIndex/JoinOnValue (⋈).

All shape/sparsity metadata lives on the nodes so the optimizer runs as pure
Python before any tracing.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from matrel_tpu.core.blockmatrix import BlockMatrix
from matrel_tpu.ir import stats

_ids = itertools.count()

ELEMWISE_OPS = ("add", "sub", "mul", "div", "min", "max")
AGG_KINDS = ("sum", "count", "avg", "max", "min")
AGG_AXES = ("row", "col", "all", "diag")
SCALAR_OPS = ("add", "mul", "pow")


@dataclasses.dataclass(frozen=True)
class MatExpr:
    """One IR node. Immutable; children are MatExpr instances.

    kind: node type tag.
    children: operand expressions.
    shape: logical output shape.
    nnz: estimated structural nonzeros (None = dense/unknown).
    attrs: kind-specific attributes (scalar value, agg kind/axis,
      predicate/merge callables, strategy hint, …).
    """

    kind: str
    children: Tuple["MatExpr", ...]
    shape: Tuple[int, int]
    nnz: Optional[int]
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    uid: int = dataclasses.field(default_factory=lambda: next(_ids))

    # equality by identity: exprs are DAG nodes, not values
    def __eq__(self, other):  # noqa: D105
        return self is other

    def __hash__(self):
        return self.uid

    # -- metadata ----------------------------------------------------------

    @property
    def density(self) -> float:
        return stats.density_of(self.nnz, self.shape)

    def with_attrs(self, **kw: Any) -> "MatExpr":
        a = dict(self.attrs)
        a.update(kw)
        return dataclasses.replace(self, attrs=a, uid=next(_ids))

    def with_children(self, children: Tuple["MatExpr", ...]) -> "MatExpr":
        return dataclasses.replace(self, children=tuple(children), uid=next(_ids))

    # -- DSL (mirrors the reference's Dataset implicit methods) ------------

    def t(self) -> "MatExpr":
        return transpose(self)

    def multiply(self, other) -> "MatExpr":
        return matmul(self, as_expr(other))

    def matmul(self, other) -> "MatExpr":
        return matmul(self, as_expr(other))

    def add(self, other) -> "MatExpr":
        return elemwise("add", self, as_expr(other))

    def subtract(self, other) -> "MatExpr":
        return elemwise("sub", self, as_expr(other))

    def elem_multiply(self, other) -> "MatExpr":
        return elemwise("mul", self, as_expr(other))

    def divide(self, other) -> "MatExpr":
        return elemwise("div", self, as_expr(other))

    def elem_min(self, other) -> "MatExpr":
        return elemwise("min", self, as_expr(other))

    def elem_max(self, other) -> "MatExpr":
        return elemwise("max", self, as_expr(other))

    def add_scalar(self, s: float) -> "MatExpr":
        return scalar_op("add", self, s)

    def multiply_scalar(self, s: float) -> "MatExpr":
        return scalar_op("mul", self, s)

    def power(self, p: float) -> "MatExpr":
        return scalar_op("pow", self, p)

    def row_sum(self) -> "MatExpr":
        return agg(self, "sum", "row")

    def col_sum(self) -> "MatExpr":
        return agg(self, "sum", "col")

    def sum(self) -> "MatExpr":
        return agg(self, "sum", "all")

    def trace(self) -> "MatExpr":
        return agg(self, "sum", "diag")

    def row_max(self) -> "MatExpr":
        return agg(self, "max", "row")

    def row_min(self) -> "MatExpr":
        return agg(self, "min", "row")

    def col_max(self) -> "MatExpr":
        return agg(self, "max", "col")

    def col_min(self) -> "MatExpr":
        return agg(self, "min", "col")

    def row_count(self) -> "MatExpr":
        return agg(self, "count", "row")

    def col_count(self) -> "MatExpr":
        return agg(self, "count", "col")

    def row_avg(self) -> "MatExpr":
        return agg(self, "avg", "row")

    def col_avg(self) -> "MatExpr":
        return agg(self, "avg", "col")

    def norm(self, kind: str = "fro") -> "MatExpr":
        """Matrix norm as a (1,1) expression — pure sugar over existing
        nodes (so every rewrite applies): "fro" = sqrt(Σ a²), "l1" =
        Σ|a| (entrywise), "max" = max|a|."""
        if kind == "fro":
            return scalar_op("pow", agg(elemwise("mul", self, self),
                                        "sum", "all"), 0.5)
        # |a| = max(a, -a): exact, no under/overflow from squaring, and
        # sparsity-preserving (max(0, 0) = 0)
        if kind in ("l1", "max"):
            absa = elemwise("max", self, self.multiply_scalar(-1.0))
            return agg(absa, "sum" if kind == "l1" else "max", "all")
        raise ValueError(f"unknown norm kind {kind!r} "
                         "(expected 'fro', 'l1', or 'max')")

    def inverse(self) -> "MatExpr":
        return inverse(self)

    def solve(self, b, assume: str = "general") -> "MatExpr":
        return solve(self, as_expr(b), assume=assume)

    def vec(self) -> "MatExpr":
        return vec(self)

    def rank_one_update(self, u, v) -> "MatExpr":
        return rank_one_update(self, as_expr(u), as_expr(v))

    def select_value(self, predicate: Callable, fill: float = 0.0) -> "MatExpr":
        return select_value(self, predicate, fill)

    def select_index(self, *, rows=None, cols=None) -> "MatExpr":
        return select_index(self, rows=rows, cols=cols)

    def join_on_index(self, other, merge: Callable) -> "MatExpr":
        return join_on_index(self, as_expr(other), merge)

    def join_on_value(self, other, merge: Callable, predicate=None) -> "MatExpr":
        return join_on_value(self, as_expr(other), merge, predicate)

    def __matmul__(self, other):
        return self.multiply(other)

    def __add__(self, other):
        if isinstance(other, (int, float)):
            return self.add_scalar(other)
        return self.add(other)

    def __sub__(self, other):
        if isinstance(other, (int, float)):
            return self.add_scalar(-other)
        return self.subtract(other)

    def __mul__(self, other):
        if isinstance(other, (int, float)):
            return self.multiply_scalar(other)
        return self.elem_multiply(other)

    def __rmul__(self, other):
        if isinstance(other, (int, float)):
            return self.multiply_scalar(other)
        return NotImplemented

    def __truediv__(self, other):
        if isinstance(other, (int, float)):
            return self.multiply_scalar(1.0 / other)
        return self.divide(other)

    # -- actions -----------------------------------------------------------

    def compute(self, session=None) -> BlockMatrix:
        """Optimize + jit + execute. The Spark 'action' analogue."""
        from matrel_tpu.session import get_or_create_session
        sess = session or get_or_create_session()
        return sess.compute(self)

    def to_numpy(self, session=None):
        return self.compute(session).to_numpy()

    def optimized(self, config=None) -> "MatExpr":
        from matrel_tpu.ir.rules import optimize
        return optimize(self, config)

    def explain(self, config=None) -> str:
        """Pretty-print logical and optimized plans (Dataset.explain analogue)."""
        opt = self.optimized(config)
        return ("== Logical plan ==\n" + pretty(self)
                + "\n== Optimized plan ==\n" + pretty(opt))

    def __repr__(self):
        return f"MatExpr<{self.kind} {self.shape} nnz={self.nnz}>"


# -- constructors (shape/sparsity inference lives here) ---------------------


def as_expr(x: Union[MatExpr, BlockMatrix]) -> MatExpr:
    if isinstance(x, MatExpr):
        return x
    if isinstance(x, BlockMatrix):
        return leaf(x)
    # sparse leaves (BlockSparseMatrix, COOMatrix) lift through their
    # own .expr() — so S1.multiply(S2) builds the S×S matmul node the
    # SpGEMM dispatch reads, without an import cycle here
    make = getattr(x, "expr", None)
    if callable(make):
        e = make()
        if isinstance(e, MatExpr):
            return e
    raise TypeError(f"cannot lift {type(x)} into MatExpr")


def leaf(m: BlockMatrix) -> MatExpr:
    return MatExpr("leaf", (), tuple(m.shape), m.nnz, {"matrix": m})


def transpose(a: MatExpr) -> MatExpr:
    return MatExpr("transpose", (a,), (a.shape[1], a.shape[0]), a.nnz)


def matmul(a: MatExpr, b: MatExpr) -> MatExpr:
    if a.shape[1] != b.shape[0]:
        raise ValueError(f"matmul shape mismatch: {a.shape} x {b.shape}")
    n, k, m = a.shape[0], a.shape[1], b.shape[1]
    return MatExpr("matmul", (a, b), (n, m),
                   stats.matmul_out_nnz(n, k, m, a.nnz, b.nnz))


def elemwise(op: str, a: MatExpr, b: MatExpr) -> MatExpr:
    if op not in ELEMWISE_OPS:
        raise ValueError(f"unknown elementwise op {op}")
    if a.shape != b.shape:
        # allow (n,1)/(1,m) broadcast against (n,m) — used by normalisation
        bcast_ok = (
            (a.shape[0] == b.shape[0] and (a.shape[1] == 1 or b.shape[1] == 1))
            or (a.shape[1] == b.shape[1] and (a.shape[0] == 1 or b.shape[0] == 1))
            or b.shape == (1, 1) or a.shape == (1, 1)
        )
        if not bcast_ok:
            raise ValueError(f"elementwise shape mismatch: {a.shape} vs {b.shape}")
    shape = (max(a.shape[0], b.shape[0]), max(a.shape[1], b.shape[1]))
    da, db = a.density, b.density
    if op in ("mul", "div"):
        d = stats.elemmul_density(da, db) if op == "mul" else da
    else:
        d = stats.add_density(da, db)
    nnz = None if (a.nnz is None and b.nnz is None) else stats.nnz_from_density(d, shape)
    return MatExpr("elemwise", (a, b), shape, nnz, {"op": op})


def scalar_op(op: str, a: MatExpr, s: float) -> MatExpr:
    if op not in SCALAR_OPS:
        raise ValueError(f"unknown scalar op {op}")
    if op == "mul":
        nnz = a.nnz if s != 0 else 0
    elif op == "add":
        nnz = a.nnz if s == 0 else None  # adding a scalar densifies
    else:  # pow
        nnz = a.nnz
    return MatExpr("scalar", (a,), a.shape, nnz, {"op": op, "value": float(s)})


def agg(a: MatExpr, kind: str, axis: str) -> MatExpr:
    if kind not in AGG_KINDS:
        raise ValueError(f"unknown agg kind {kind}")
    if axis not in AGG_AXES:
        raise ValueError(f"unknown agg axis {axis}")
    if axis == "diag" and a.shape[0] != a.shape[1]:
        raise ValueError(f"diag aggregate needs a square matrix, got {a.shape}")
    shape = {"row": (a.shape[0], 1), "col": (1, a.shape[1]),
             "all": (1, 1), "diag": (1, 1)}[axis]
    return MatExpr("agg", (a,), shape, None, {"agg": kind, "axis": axis})


def vec(a: MatExpr) -> MatExpr:
    """Column-major vectorisation vec(A): (n,m) → (n*m, 1)."""
    return MatExpr("vec", (a,), (a.shape[0] * a.shape[1], 1), a.nnz)


def rank_one_update(a: MatExpr, u: MatExpr, v: MatExpr) -> MatExpr:
    """A + u·vᵀ with u:(n,1), v:(m,1)."""
    n, m = a.shape
    if u.shape != (n, 1) or v.shape != (m, 1):
        raise ValueError(
            f"rank_one_update expects u:({n},1) v:({m},1); got {u.shape}, {v.shape}")
    return MatExpr("rank1", (a, u, v), a.shape, None)


def inverse(a: MatExpr) -> MatExpr:
    """A⁻¹ for square A. Dense local solve on the logical (unpadded)
    matrix — the analogue of the reference's driver-side inverse in the
    normal-equations workload ((XᵀX)⁻¹Xᵀy, SURVEY.md §2 workloads row):
    the Gram matrix is small, so the reference inverts it locally, not
    distributively. Prefer ``solve(a, b)`` over ``inverse(a) @ b`` —
    the optimizer rewrites the latter into the former (R7).
    """
    n, m = a.shape
    if n != m:
        raise ValueError(f"inverse needs a square matrix, got {a.shape}")
    return MatExpr("inverse", (a,), a.shape, None)


def solve(a: MatExpr, b: MatExpr, assume: str = "general") -> MatExpr:
    """X = A⁻¹·B (solve A·X = B) for square A, on the logical shapes.

    ``assume="pos"`` takes a Cholesky factorisation instead of LU —
    right for the normal-equations Gram matrix (SPD), ~2× cheaper and
    numerically tighter. ``"general"`` (default) is LU.
    """
    if assume not in ("general", "pos"):
        raise ValueError(f"solve assume must be 'general' or 'pos', "
                         f"got {assume!r}")
    n, m = a.shape
    if n != m:
        raise ValueError(f"solve needs a square lhs, got {a.shape}")
    if b.shape[0] != n:
        raise ValueError(f"solve shape mismatch: {a.shape} x {b.shape}")
    return MatExpr("solve", (a, b), b.shape, None, {"assume": assume})


def select_value(a: MatExpr, predicate: Callable, fill: float = 0.0) -> MatExpr:
    """Relational σ on entry values: keep entries where predicate(v) holds.

    Static-shape semantics (XLA constraint, flagged in SURVEY.md §7.6): the
    result is a same-shaped matrix with non-matching entries set to ``fill``,
    not a shrunk relation. ``fill=0`` keeps sparsity algebra exact.
    """
    return MatExpr("select_value", (a,), a.shape, a.nnz,
                   {"predicate": predicate, "fill": float(fill)})


def select_index(a: MatExpr, *, rows=None, cols=None) -> MatExpr:
    """Relational σ on indices: keep rows/cols where the predicate holds.

    rows/cols are callables over index arrays (vectorised, traceable) or
    None. Non-selected entries become 0 (static shapes).
    """
    return MatExpr("select_index", (a,), a.shape, a.nnz,
                   {"rows": rows, "cols": cols})


def join_on_index(a: MatExpr, b: MatExpr, merge) -> MatExpr:
    """⋈ on block/entry index equality: C[i,j] = merge(A[i,j], B[i,j]).

    The cogroup-style join of two co-partitioned matrices (SURVEY.md §2
    "Physical: relational execs"). ``merge`` is a traceable binary fn OR
    a structured string ("left"/"right"/"add"/"mul") — structured kinds
    let the planner infer the output dtype (jnp promotion).
    """
    if a.shape != b.shape:
        raise ValueError(f"join_on_index shape mismatch: {a.shape} vs {b.shape}")
    merge_kind, merge_fn = resolve_join_merge(merge)
    return MatExpr("join_index", (a, b), a.shape, None,
                   {"merge": merge_fn, "merge_kind": merge_kind})


JOIN_PREDS = ("eq", "lt", "le", "gt", "ge")
JOIN_MERGES = ("left", "right", "add", "mul")


def resolve_join_pred(pred):
    """(pred_kind, callable) for a structured-or-callable predicate.
    Structured kinds compare va ? vb: "lt" means va < vb."""
    if pred is None or callable(pred):
        return None, pred
    if pred not in JOIN_PREDS:
        raise ValueError(f"unknown join predicate {pred!r}; expected a "
                         f"callable or one of {JOIN_PREDS}")
    import operator
    fn = {"eq": operator.eq, "lt": operator.lt, "le": operator.le,
          "gt": operator.gt, "ge": operator.ge}[pred]
    return pred, fn


def resolve_join_merge(merge):
    """(merge_kind, callable) for a structured-or-callable merge."""
    if callable(merge):
        return None, merge
    if merge not in JOIN_MERGES:
        raise ValueError(f"unknown join merge {merge!r}; expected a "
                         f"callable or one of {JOIN_MERGES}")
    def _take_left(a, b):
        import jax.numpy as jnp
        # broadcast WITHOUT arithmetic on b: a + 0*b turns a non-finite
        # discarded operand into NaN (inf·0)
        return a + jnp.zeros_like(b)

    fn = {"left": _take_left,
          "right": lambda a, b: _take_left(b, a),
          "add": lambda a, b: a + b,
          "mul": lambda a, b: a * b}[merge]
    return merge, fn


def join_on_value(a: MatExpr, b: MatExpr, merge,
                  predicate=None) -> MatExpr:
    """⋈ on values: pairs (A[i,j], B[k,l]) where predicate(va, vb).

    Full value-join output is |A|x|B| pairs — unrepresentable statically.
    Faithful static-shape semantics: the result is the (n*m_A) x (n*m_B)
    PAIR MATRIX restricted to merge values where the predicate holds, as
    a lazy node. Materialising it is capped by
    config.join_pair_cap_entries; AGGREGATED value-joins
    (agg(join_on_value(...), ...)) never materialise the pair matrix —
    with STRUCTURED predicate/merge (predicate in "eq"/"lt"/"le"/"gt"/
    "ge" on va ? vb, merge in "left"/"right"/"add"/"mul") they stream in
    O((na+nb)·log nb) via the executor's sort-based path (the
    reference's scalable value-join; SURVEY.md §2 relational execs),
    and with callables they fall back to capped chunkwise enumeration.
    For aligned-entry joins use join_on_index.
    """
    pred_kind, pred_fn = resolve_join_pred(predicate)
    merge_kind, merge_fn = resolve_join_merge(merge)
    na = a.shape[0] * a.shape[1]
    nb = b.shape[0] * b.shape[1]
    return MatExpr("join_value", (a, b), (na, nb), None,
                   {"merge": merge_fn, "predicate": pred_fn,
                    "merge_kind": merge_kind, "pred_kind": pred_kind})


# -- utilities --------------------------------------------------------------


def leaves(e: MatExpr) -> List[MatExpr]:
    """All leaf nodes in evaluation order (deduped by identity)."""
    seen: Dict[int, MatExpr] = {}

    def walk(n: MatExpr):
        if n.kind == "leaf":
            seen.setdefault(n.uid, n)
        for c in n.children:
            walk(c)

    walk(e)
    return list(seen.values())


def pretty(e: MatExpr, indent: int = 0, mesh=None,
           _lmemo: Optional[dict] = None, config=None) -> str:
    """Plan printer. With ``mesh`` given, each non-canonically-laid node
    is annotated ``layout=row/col/rep`` from planner.infer_layout — the
    physical-EXPLAIN view of the co-partitioning credit (round 5), next
    to the strategy provenance it drives. Pass the PLAN's config so the
    printed layouts are the ones the planner actually used (the COO
    "rep" claim is config-dependent — review r5)."""
    pad = "  " * indent
    extra = ""
    if e.kind == "elemwise":
        extra = f" op={e.attrs['op']}"
    elif e.kind == "scalar":
        extra = f" op={e.attrs['op']} v={e.attrs['value']}"
    elif e.kind == "agg":
        extra = f" {e.attrs['agg']}/{e.attrs['axis']}"
    elif e.kind == "matmul" and "strategy" in e.attrs:
        extra = f" strategy={e.attrs['strategy']}"
        if "strategy_source" in e.attrs:
            extra += f"[{e.attrs['strategy_source']}]"
        if "precision_tier" in e.attrs:
            extra += f" precision={e.attrs['precision_tier']}"
    elif e.kind in ("join_rows", "join_cols") and "replicate" in e.attrs:
        extra = f" replicate={e.attrs['replicate']}"
    elif e.kind == "join_value":
        mk = e.attrs.get("merge_kind") or "<callable>"
        pk = e.attrs.get("pred_kind") or (
            "<callable>" if e.attrs.get("predicate") else "always")
        extra = f" merge={mk} pred={pk}"
    if mesh is not None:
        from matrel_tpu.parallel import planner as _pl   # lazy: no cycle
        if _lmemo is None:
            _lmemo = {}
        lay = _pl.infer_layout(e, mesh, _lmemo, config)
        if lay != "2d":
            extra += f" layout={lay}"
    line = f"{pad}{e.kind}{extra} shape={e.shape} nnz={e.nnz}\n"
    return line + "".join(pretty(c, indent + 1, mesh, _lmemo, config)
                          for c in e.children)
