# matrel_tpu developer entry points.
#
# test       — full CPU suite on the simulated 8-device mesh
# soak       — oracle fuzz batteries on CPU (fast sanity)
# soak-tpu   — on-chip soak with relay-wedge-safe probe/timeouts;
#              result appended to PROGRESS.jsonl (tools/soak_guard.py).
#              The real-chip run is the only place Mosaic bf16 behavior
#              is exercised — run it after any kernel change.
# multihost  — 2- and 4-process Gloo collectives (DCN shape)
# native     — build the C++ optimizer/ingestion core
# bench      — the driver's headline metric (TPU; wedge-safe)
# obs-report — aggregate the repo's query/bench/soak event log
#              (.matrel_events.jsonl — the history-server analogue)

PY ?= python
SEEDS ?= 10
OBS_LOG ?= .matrel_events.jsonl

.PHONY: test soak soak-tpu multihost native bench tpu-batch obs-report

test:
	$(PY) -m pytest tests/ -q

soak:
	$(PY) tools/soak.py all --seeds 25

soak-tpu:
	$(PY) tools/soak_guard.py --seeds $(SEEDS)

multihost:
	$(PY) -m pytest tests/test_multihost.py -q

native:
	$(MAKE) -C native

bench:
	$(PY) bench.py

tpu-batch:
	sh tools/tpu_batch.sh

obs-report:
	$(PY) -m matrel_tpu history --summary --log $(OBS_LOG)
