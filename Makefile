# matrel_tpu developer entry points.
#
# lint       — matlint (AST hazard rules, tools/matlint.py) + the
#              concurrency sanitizer's static half (lock-order /
#              hold-span analysis, tools/lockcheck.py; LK1xx rules,
#              docs/CONCURRENCY.md) + the static-verifier self-check
#              over the plan-snapshot corpus (tools/plan_verify.py).
#              Runs repo-wide; rc != 0 on any finding/diagnostic.
#              `test` depends on it, and tests/test_matlint.py +
#              tests/test_lockcheck.py re-run it in-process so the
#              tier-1 pytest path cannot silently skip it either.
# test       — full CPU suite on the simulated 8-device mesh
# soak       — oracle fuzz batteries on CPU (fast sanity)
# soak-tpu   — on-chip soak with relay-wedge-safe probe/timeouts;
#              result appended to PROGRESS.jsonl (tools/soak_guard.py).
#              The real-chip run is the only place Mosaic bf16 behavior
#              is exercised — run it after any kernel change.
# multihost  — 2- and 4-process Gloo collectives (DCN shape)
# native     — build the C++ optimizer/ingestion core
# bench      — the driver's headline metric (TPU; wedge-safe)
# obs-report — aggregate the repo's query/bench/soak event log
#              (.matrel_events.jsonl — the history-server analogue);
#              --check on the summary exits nonzero on any UN-CLEARED
#              SLO alert (a log ending mid-incident must not read
#              green), then the round-9 smokes over the same log: the
#              cost-model drift audit (history --drift --check), the
#              closed-loop gate (history --coeffs --check — a firing
#              rank flag with no re-plan round fails the report) and a
#              chrome-trace export of the tracing spans, then the
#              tier-4 audit-replay gate (why --audit: sampled served
#              answers re-executed fresh and proved within their
#              stamped bounds). Point it at a dry-drill log with
#              OBS_LOG=/tmp/matrel_batch_dry/events.jsonl

PY ?= python
SEEDS ?= 10
OBS_LOG ?= .matrel_events.jsonl

.PHONY: test lint soak soak-tpu multihost native bench tpu-batch \
        tpu-batch-dry obs-report chaos

lint:
	$(PY) tools/matlint.py
	$(PY) tools/lockcheck.py
	$(PY) tools/plan_verify.py

test: lint
	$(PY) -m pytest tests/ -q

soak:
	$(PY) tools/soak.py all --seeds 25

# resilience acceptance: a mixed serve stream under a seeded fault
# schedule (every instrumented site) must converge-to-correct-or-
# typed-failure with zero hangs (tools/chaos_drill.py), then the
# randomized chaos soak battery on top (docs/RESILIENCE.md)
chaos:
	$(PY) tools/chaos_drill.py
	$(PY) tools/soak.py chaos --seeds 25

soak-tpu:
	$(PY) tools/soak_guard.py --seeds $(SEEDS)

multihost:
	$(PY) -m pytest tests/test_multihost.py -q

native:
	$(MAKE) -C native

bench:
	$(PY) bench.py

tpu-batch:
	sh tools/tpu_batch.sh

# fire-drill: the WHOLE staged relay-recovery batch on the CPU backend
# at toy sizes (VERDICT r5 Next #2) — proves every step runs and emits
# its parseable artifact, so a real relay window is spent measuring,
# not debugging the harness. tests/test_batch_dry.py asserts the
# artifacts.
tpu-batch-dry:
	sh tools/tpu_batch.sh --dry

obs-report:
	$(PY) -m matrel_tpu history --summary --check --log $(OBS_LOG)
	$(PY) -m matrel_tpu history --drift --check --log $(OBS_LOG)
	$(PY) -m matrel_tpu history --coeffs --check --log $(OBS_LOG)
	$(PY) -m matrel_tpu trace --export chrome --log $(OBS_LOG) \
		--out $(OBS_LOG).chrome.json
	$(PY) -m matrel_tpu why --audit --sample 8 --check
