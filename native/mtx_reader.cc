// matrel_tpu native ingestion core: MatrixMarket + COO-CSV parsers.
//
// The reference's ingestion path reads coordinate text (HDFS CSV /
// MatrixMarket) into block RDDs on the JVM (SURVEY.md §2 "Block
// representation"); its throughput is set by JVM text parsing. Here the
// equivalent hot loop is host-side text→COO parsing before device
// placement, so it lives in C++: one fread of the whole file, then a
// pointer scan with a hand-rolled float parser (glibc strtod costs
// ~200ns/number; this is ~5× faster) — multithreaded on multicore hosts.
//
// C ABI only — consumed with ctypes (utils/native.py), no pybind11.
// Handle-based: `open` slurps the file ONCE and parses the header;
// `fill` parses the data section into caller buffers; `close` frees.
// Indices are returned 0-based. Symmetry expansion is left to the Python
// side (vectorised numpy mirror), so buffers are sized by the STORED nnz.

#include <algorithm>
#include <cctype>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <utility>
#include <vector>

namespace {

// Whole-file read. Returns false on open/read failure.
bool slurp(const char* path, std::string* out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  long sz = std::ftell(f);
  if (sz < 0) {
    std::fclose(f);
    return false;
  }
  std::fseek(f, 0, SEEK_SET);
  out->resize(static_cast<size_t>(sz));
  size_t got = sz ? std::fread(&(*out)[0], 1, static_cast<size_t>(sz), f) : 0;
  std::fclose(f);
  out->resize(got);
  return true;
}

const char* skip_ws(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  return p;
}

const char* next_line(const char* p, const char* end) {
  while (p < end && *p != '\n') ++p;
  return p < end ? p + 1 : end;
}

// Flags shared with utils/native.py.
constexpr int32_t kSymmetric = 1;
constexpr int32_t kPattern = 2;
constexpr int32_t kSkew = 4;
constexpr int32_t kComplexUnsupported = 8;
constexpr int32_t kDenseArray = 16;

// -- fast number parsing ----------------------------------------------------

inline const char* parse_int_fast(const char* p, const char* end,
                                  int64_t* out) {
  bool neg = false;
  if (p < end && (*p == '-' || *p == '+')) {
    neg = *p == '-';
    ++p;
  }
  if (p >= end || *p < '0' || *p > '9') return nullptr;
  int64_t v = 0;
  while (p < end && *p >= '0' && *p <= '9') v = v * 10 + (*p++ - '0');
  *out = neg ? -v : v;
  return p;
}

const double kPow10[] = {1e0,  1e1,  1e2,  1e3,  1e4,  1e5,  1e6,  1e7,
                         1e8,  1e9,  1e10, 1e11, 1e12, 1e13, 1e14, 1e15,
                         1e16, 1e17, 1e18, 1e19, 1e20, 1e21, 1e22};

inline const char* parse_double_fast(const char* p, const char* end,
                                     double* out) {
  const char* start = p;
  bool neg = false;
  if (p < end && (*p == '-' || *p == '+')) {
    neg = *p == '-';
    ++p;
  }
  uint64_t mant = 0;
  int digits = 0, frac = 0;
  bool any = false;
  while (p < end && *p >= '0' && *p <= '9') {
    mant = mant * 10 + static_cast<uint64_t>(*p - '0');
    ++digits;
    ++p;
    any = true;
  }
  if (p < end && *p == '.') {
    ++p;
    while (p < end && *p >= '0' && *p <= '9') {
      mant = mant * 10 + static_cast<uint64_t>(*p - '0');
      ++digits;
      ++frac;
      ++p;
      any = true;
    }
  }
  if (!any) return nullptr;
  int exp10 = -frac;
  if (p < end && (*p == 'e' || *p == 'E' || *p == 'd' || *p == 'D')) {
    int64_t e = 0;
    const char* q = parse_int_fast(p + 1, end, &e);
    if (q) {
      exp10 += static_cast<int>(e);
      p = q;
    }
  }
  // Fast path: mantissa→double rounds once, pow10 scale rounds once →
  // ≤1 ulp total in double, invisible after the float32 cast downstream.
  // uint64 holds 19 digits without overflow; harder cases → strtod.
  if (digits <= 19 && exp10 >= -22 && exp10 <= 22) {
    double v = static_cast<double>(mant);
    v = exp10 >= 0 ? v * kPow10[exp10] : v / kPow10[-exp10];
    *out = neg ? -v : v;
    return p;
  }
  char* q = nullptr;
  *out = std::strtod(start, &q);
  return q == start ? nullptr : q;
}

// -- coordinate-section parsing ---------------------------------------------

// One tokenizer for every consumer. `sink(i, j, v)` returns false on
// overflow; parse returns false on malformed input or sink refusal.
template <typename Sink>
bool parse_coord(const char* p, const char* end, bool pattern, int64_t base,
                 Sink&& sink) {
  while (p < end) {
    p = skip_ws(p, end);
    if (p >= end) break;
    if (*p == '\n') {
      ++p;
      continue;
    }
    if (*p == '%' || *p == '#') {
      p = next_line(p, end);
      continue;
    }
    int64_t i = 0, j = 0;
    const char* q = parse_int_fast(p, end, &i);
    if (!q) return false;
    while (q < end && (*q == ',' || *q == ' ' || *q == '\t')) ++q;
    q = parse_int_fast(q, end, &j);
    if (!q) return false;
    double v = 1.0;
    if (!pattern) {
      while (q < end && (*q == ',' || *q == ' ' || *q == '\t')) ++q;
      q = parse_double_fast(q, end, &v);
      if (!q) return false;
    }
    p = next_line(q, end);
    if (!sink(i - base, j - base, v)) return false;
  }
  return true;
}

struct Entry {
  int64_t i, j;
  double v;
};

// Parse [p, end): one chunk per hardware thread on multicore hosts
// (per-thread vectors, stitched in order), straight into the caller's
// buffers when single-threaded. Returns total entries, -1 on error.
int64_t parse_coord_parallel(const char* p, const char* end, bool pattern,
                             int64_t base, int64_t expected_hint,
                             int64_t* ri, int64_t* ci, double* vals,
                             int64_t capacity) {
  const int64_t bytes = end - p;
  unsigned hw = std::thread::hardware_concurrency();
  int nthreads = static_cast<int>(std::max(1u, std::min(hw, 16u)));
  if (bytes < (1 << 20)) nthreads = 1;  // small files: skip thread setup
  if (nthreads == 1) {
    int64_t n = 0;
    bool ok = parse_coord(p, end, pattern, base,
                          [&](int64_t i, int64_t j, double v) {
                            if (n >= capacity) return false;
                            ri[n] = i;
                            ci[n] = j;
                            vals[n] = v;
                            ++n;
                            return true;
                          });
    return ok ? n : -1;
  }
  std::vector<const char*> bounds(nthreads + 1);
  bounds[0] = p;
  bounds[nthreads] = end;
  for (int t = 1; t < nthreads; ++t) {
    const char* cut = p + bytes * t / nthreads;
    while (cut < end && *cut != '\n') ++cut;
    bounds[t] = cut < end ? cut + 1 : end;
  }
  std::vector<std::vector<Entry>> parts(nthreads);
  std::vector<char> oks(nthreads, 1);
  int64_t reserve = expected_hint > 0 ? expected_hint / nthreads + 16
                                      : bytes / (8 * nthreads) + 16;
  auto work = [&](int t) {
    parts[t].reserve(static_cast<size_t>(reserve));
    oks[t] = parse_coord(bounds[t], bounds[t + 1], pattern, base,
                         [&parts, t](int64_t i, int64_t j, double v) {
                           parts[t].push_back({i, j, v});
                           return true;
                         })
                 ? 1
                 : 0;
  };
  std::vector<std::thread> threads;
  threads.reserve(nthreads);
  for (int t = 0; t < nthreads; ++t) threads.emplace_back(work, t);
  for (auto& th : threads) th.join();
  int64_t total = 0;
  for (int t = 0; t < nthreads; ++t) {
    if (!oks[t]) return -1;
    total += static_cast<int64_t>(parts[t].size());
  }
  if (total > capacity) return -1;
  int64_t off = 0;
  for (int t = 0; t < nthreads; ++t) {
    for (const Entry& e : parts[t]) {
      ri[off] = e.i;
      ci[off] = e.j;
      vals[off] = e.v;
      ++off;
    }
  }
  return total;
}

// -- handles ----------------------------------------------------------------

struct ParseHandle {
  std::string buf;
  size_t data_off = 0;  // offset of the data section into buf
  int64_t rows = 0, cols = 0, nnz = 0;
  int32_t flags = 0;
  int64_t base = 0;  // 1 for MatrixMarket, 0 for raw COO text
};

// Parses the MatrixMarket banner/comments/size line into h. Returns false
// on malformed header.
bool parse_mtx_header(ParseHandle* h) {
  const char* begin = h->buf.data();
  const char* p = begin;
  const char* end = p + h->buf.size();
  if (h->buf.size() < 14 || std::strncmp(p, "%%MatrixMarket", 14) != 0)
    return false;
  const char* eol = p;
  while (eol < end && *eol != '\n') ++eol;
  std::string banner(p, eol - p);
  for (auto& ch : banner) ch = static_cast<char>(std::tolower(ch));
  if (banner.find("array") != std::string::npos) h->flags |= kDenseArray;
  if (banner.find("pattern") != std::string::npos) h->flags |= kPattern;
  if (banner.find("complex") != std::string::npos)
    h->flags |= kComplexUnsupported;
  if (banner.find("skew-symmetric") != std::string::npos)
    h->flags |= kSkew | kSymmetric;
  else if (banner.find("symmetric") != std::string::npos ||
           banner.find("hermitian") != std::string::npos)
    h->flags |= kSymmetric;
  p = next_line(p, end);
  while (p < end && *p == '%') p = next_line(p, end);
  char* q = nullptr;
  h->rows = std::strtoll(p, &q, 10);
  h->cols = std::strtoll(q, &q, 10);
  h->nnz = (h->flags & kDenseArray) ? h->rows * h->cols
                                    : std::strtoll(q, &q, 10);
  if (h->rows < 0 || h->cols < 0 || h->nnz < 0 || q == p) return false;
  // Data starts after the size line's LAST parsed number — strtoll may
  // have skipped blank lines between comments and the size line, so
  // advancing from `p` could leave data_off pointing at the size line
  // itself (corrupting dense-array payloads).
  h->data_off = static_cast<size_t>(next_line(q, end) - begin);
  h->base = 1;
  return true;
}

}  // namespace

extern "C" {

// Open a MatrixMarket file: slurp once, parse the header. Returns an
// opaque handle (NULL on open/parse failure) and fills rows/cols/nnz
// (STORED entry count) + format flags.
void* matrel_mtx_open(const char* path, int64_t* rows, int64_t* cols,
                      int64_t* nnz, int32_t* flags) {
  auto* h = new ParseHandle();
  if (!slurp(path, &h->buf) || !parse_mtx_header(h)) {
    delete h;
    return nullptr;
  }
  *rows = h->rows;
  *cols = h->cols;
  *nnz = h->nnz;
  *flags = h->flags;
  return h;
}

// Open an "i,j,value" COO text file ('#'/'%' comments; separators ','
// or whitespace). Fills *count with the number of data lines.
void* matrel_coo_csv_open(const char* path, int64_t* count) {
  auto* h = new ParseHandle();
  if (!slurp(path, &h->buf)) {
    delete h;
    return nullptr;
  }
  const char* p = h->buf.data();
  const char* end = p + h->buf.size();
  int64_t n = 0;
  while (p < end) {
    p = skip_ws(p, end);
    if (p < end && *p != '\n' && *p != '#' && *p != '%') ++n;
    p = next_line(p, end);
  }
  h->nnz = n;
  *count = n;
  return h;
}

// Parse the opened file's data section (0-based indices) into caller
// buffers of `capacity` elements. Pattern entries yield 1.0; dense
// "array" payloads yield column-major coordinates. Returns entries
// written, -1 on malformed input/overflow/unsupported field.
int64_t matrel_parse_fill(void* handle, int64_t* ri, int64_t* ci,
                          double* vals, int64_t capacity) {
  auto* h = static_cast<ParseHandle*>(handle);
  if (!h || (h->flags & kComplexUnsupported)) return -1;
  const char* p = h->buf.data() + h->data_off;
  const char* end = h->buf.data() + h->buf.size();
  if (h->flags & kDenseArray) {
    if (h->nnz > capacity) return -1;
    int64_t n = 0;
    for (int64_t j = 0; j < h->cols; ++j) {
      for (int64_t i = 0; i < h->rows; ++i) {
        p = skip_ws(p, end);
        while (p < end && *p == '\n') p = skip_ws(p + 1, end);
        double v = 0.0;
        const char* q = parse_double_fast(p, end, &v);
        if (!q) return -1;
        p = q;
        ri[n] = i;
        ci[n] = j;
        vals[n] = v;
        ++n;
      }
    }
    return n;
  }
  int64_t n = parse_coord_parallel(p, end, h->flags & kPattern, h->base,
                                   h->nnz, ri, ci, vals, capacity);
  // A coordinate header states its entry count; enforce it.
  if (h->base == 1 && n != h->nnz) return -1;
  return n;
}

void matrel_parse_close(void* handle) {
  delete static_cast<ParseHandle*>(handle);
}

}  // extern "C"
