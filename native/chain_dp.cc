// matrel_tpu native optimizer core: matrix-chain DP with sparsity-aware
// cost — the C++ equivalent of the reference's driver-side Catalyst
// optimizer hot loop (SURVEY.md §2 "Optimizer: matrix-chain DP"; §3.3).
//
// The reference runs this O(n³) interval DP on the Spark driver (JVM).
// For long chains the Python fallback (ir/chain.py) dominates planning
// time, so the planner calls into this library via ctypes when built
// (utils/native.py). Semantics mirror ir/chain.py + ir/stats.py exactly:
//
//   cost(i,j,s) = cost(i,s) + cost(s+1,j)
//               + 2 * rows(i) * cols(s) * cols(j) * d(i,s) * d(s+1,j)
//   d over an interval: matmul_density(d_left, d_right, k)
//                     = 1 - (1 - d_l*d_r)^k   (stable via expm1/log1p)
//
// Build: make -C native   →  libmatrel_opt.so
//
// C ABI only — consumed with ctypes, no pybind11 dependency.

#include <cmath>
#include <cstdint>
#include <vector>

namespace {

double matmul_density(double da, double db, double k) {
  double p = da * db;
  if (p <= 0.0) return 0.0;
  if (p >= 1.0) return 1.0;
  return -std::expm1(k * std::log1p(-p));
}

// Layout codes — MUST match ir/stats.py::LAYOUT_CODES.
constexpr int8_t kLay2d = 0;
constexpr int8_t kLayRow = 1;
constexpr int8_t kLayCol = 2;
constexpr int8_t kLayRep = 3;
// 4 ("other") behaves as 2d in every formula below.

// Per-device bytes to re-lay an operand into the canonical P(x, y)
// tiling (cpmm/summa input), weighted by the topology weight of the
// single mesh axis the gather rides (row-sharded gathers along y,
// col-sharded along x). Mirrors planner._to_2d_reshard/_to_2d_axis.
double to_2d_reshard(double bytes, int8_t lay, double gx, double gy,
                     double p, double wx, double wy) {
  if (lay == kLayRep) return 0.0;
  if (lay == kLayRow) return (bytes / p) * (1.0 - 1.0 / gy) * wy;
  if (lay == kLayCol) return (bytes / p) * (1.0 - 1.0 / gx) * wx;
  return 0.0;
}

// Weighted cost of a FULL-MESH replication of src bytes from an even
// p-way shard: hierarchical two-stage split, the expensive axis riding
// the small first stage (min over stage orders); uniform weights keep
// the flat closed form's float arithmetic. Mirrors
// planner._split_full_mesh exactly.
double split_full_mesh(double src, double gx, double gy, double p,
                       double wx, double wy) {
  if (wx == wy) return src * (p - 1.0) / p * wx;
  double cost_yf = wy * src * (gy - 1.0) / p + wx * src * (gx - 1.0) / gx;
  double cost_xf = wx * src * (gx - 1.0) / p + wy * src * (gy - 1.0) / gy;
  return cost_yf <= cost_xf ? cost_yf : cost_xf;
}

// Per-device weighted interconnect cost of the cheapest MM strategy for
// (n×k)·(k×m) on a gx×gy mesh, given operand layouts and per-axis
// topology weights (wx, wy); *out_lay receives the layout the argmin
// strategy emits (bmm_r → row, bmm_l → col, cpmm/rmm → 2d). MUST
// mirror ir/stats.py::comm_proxy_layout (planner.comm_cost's
// per-layout, per-axis forms, no admissibility gates) INCLUDING the
// tie-break order — the equivalence is asserted by tests/test_native.py
// over weighted grids.
double comm_proxy_layout(double n, double k, double m, double da, double db,
                         double gx, double gy, double itemsize,
                         int8_t la, int8_t lb, double wx, double wy,
                         int8_t* out_lay) {
  double p = gx * gy;
  if (p <= 1.0) {
    *out_lay = kLay2d;
    return 0.0;
  }
  double a_b = n * k * itemsize * da;
  double b_b = k * m * itemsize * db;
  double c_b = n * m * itemsize;
  double bmm_r =
      (lb == kLayRep ? 0.0 : split_full_mesh(b_b, gx, gy, p, wx, wy)) +
      (la == kLayRow || la == kLayRep
           ? 0.0
           : (a_b / p) * (1.0 - 1.0 / gy) * wy);
  double bmm_l =
      (la == kLayRep ? 0.0 : split_full_mesh(a_b, gx, gy, p, wx, wy)) +
      (lb == kLayCol || lb == kLayRep
           ? 0.0
           : (b_b / p) * (1.0 - 1.0 / gx) * wx);
  double cpmm = to_2d_reshard(a_b, la, gx, gy, p, wx, wy) +
                (lb == kLayRep ? 0.0
                               : (b_b / gy) * (gx - 1.0) / gx * wx) +
                (c_b / gx) * (gy - 1.0) / gy * wy;
  double rmm = (la == kLayRep ? 0.0
                              : (a_b / gx) * (gy - 1.0) / gy * wy) +
               (lb == kLayRep ? 0.0
                              : (b_b / gy) * (gx - 1.0) / gx * wx);
  double best = bmm_r;
  int8_t lay = kLayRow;
  if (bmm_l < best) { best = bmm_l; lay = kLayCol; }
  if (cpmm < best) { best = cpmm; lay = kLay2d; }
  if (rmm < best) { best = rmm; lay = kLay2d; }
  *out_lay = lay;
  return best;
}

int chain_dp_impl(int32_t n, const int64_t* dims, const double* dens,
                  const int8_t* lays, double gx, double gy,
                  double comm_weight, double itemsize, double wx,
                  double wy, int32_t* split_out, double* cost_out) {
  if (n <= 0 || dims == nullptr || dens == nullptr || split_out == nullptr ||
      cost_out == nullptr)
    return 1;
  if (n == 1) {
    *cost_out = 0.0;
    return 0;
  }
  std::vector<double> cost(static_cast<size_t>(n) * n, 0.0);
  std::vector<double> density(static_cast<size_t>(n) * n, 1.0);
  std::vector<int8_t> layout(static_cast<size_t>(n) * n, kLay2d);
  for (int i = 0; i < n; ++i) {
    density[i * n + i] = dens[i];
    layout[i * n + i] = lays ? lays[i] : kLay2d;
  }

  for (int span = 2; span <= n; ++span) {
    for (int i = 0; i + span - 1 < n; ++i) {
      int j = i + span - 1;
      double best = -1.0;
      int best_s = i;
      double best_d = 1.0;
      int8_t best_l = kLay2d;
      for (int s = i; s < j; ++s) {
        double dl = density[i * n + s];
        double dr = density[(s + 1) * n + j];
        double rows = static_cast<double>(dims[i]);
        double mid = static_cast<double>(dims[s + 1]);
        double colsj = static_cast<double>(dims[j + 1]);
        double step = 2.0 * rows * mid * colsj * dl * dr;
        int8_t out_lay = kLay2d;
        if (comm_weight > 0.0)
          step += comm_weight *
                  comm_proxy_layout(rows, mid, colsj, dl, dr, gx, gy,
                                    itemsize, layout[i * n + s],
                                    layout[(s + 1) * n + j], wx, wy,
                                    &out_lay);
        double total = cost[i * n + s] + cost[(s + 1) * n + j] + step;
        if (best < 0.0 || total < best) {
          best = total;
          best_s = s;
          best_d = matmul_density(dl, dr, mid);
          best_l = out_lay;
        }
      }
      cost[i * n + j] = best;
      density[i * n + j] = best_d;
      layout[i * n + j] = best_l;
      split_out[i * n + j] = best_s;
    }
  }
  *cost_out = cost[0 * n + (n - 1)];
  return 0;
}

}  // namespace

extern "C" {

// dims: n+1 entries — operand i is dims[i] x dims[i+1]
// dens: n entries   — density of operand i (1.0 = dense)
// split_out: n*n row-major; split_out[i*n+j] = optimal split s for the
//            inclusive interval [i, j] (undefined for i >= j)
// cost_out:  total optimal FLOP cost of [0, n-1]
// returns 0 on success, nonzero on bad input
int matrel_chain_dp(int32_t n, const int64_t* dims, const double* dens,
                    int32_t* split_out, double* cost_out) {
  return chain_dp_impl(n, dims, dens, nullptr, 1.0, 1.0, 0.0, 4.0, 1.0,
                       1.0, split_out, cost_out);
}

// Comm-aware variant: step cost additionally pays
// comm_weight * comm_proxy(dims, densities, gx, gy, itemsize) —
// FLOP-equivalents of the cheapest collective bill on the gx×gy mesh,
// at the canonical 2d layouts.
int matrel_chain_dp_comm(int32_t n, const int64_t* dims, const double* dens,
                         int32_t gx, int32_t gy, double comm_weight,
                         int32_t itemsize, int32_t* split_out,
                         double* cost_out) {
  if (gx <= 0 || gy <= 0 || itemsize <= 0) return 1;
  return chain_dp_impl(n, dims, dens, nullptr, static_cast<double>(gx),
                       static_cast<double>(gy), comm_weight,
                       static_cast<double>(itemsize), 1.0, 1.0, split_out,
                       cost_out);
}

// Layout-aware variant (round 5): lays is n int8 layout codes
// (ir/stats.py::LAYOUT_CODES — 0=2d, 1=row, 2=col, 3=rep, 4=other);
// the comm term gains the per-layout credits/charges and each DP
// interval tracks the layout its cheapest strategy emits.
int matrel_chain_dp_layout(int32_t n, const int64_t* dims,
                           const double* dens, const int8_t* lays,
                           int32_t gx, int32_t gy, double comm_weight,
                           int32_t itemsize, int32_t* split_out,
                           double* cost_out) {
  if (gx <= 0 || gy <= 0 || itemsize <= 0 || lays == nullptr) return 1;
  return chain_dp_impl(n, dims, dens, lays, static_cast<double>(gx),
                       static_cast<double>(gy), comm_weight,
                       static_cast<double>(itemsize), 1.0, 1.0, split_out,
                       cost_out);
}

// Topology-aware variant (round 7): wx/wy are the per-mesh-axis
// inverse-bandwidth weights (core/mesh.MeshTopology — 1.0 = ICI
// baseline, a DCN-crossing axis ≫ 1), so the comm term bills each
// strategy's collective legs on the axis they actually ride. Weights
// (1.0, 1.0) reproduce matrel_chain_dp_layout bit-identically.
int matrel_chain_dp_topo(int32_t n, const int64_t* dims,
                         const double* dens, const int8_t* lays,
                         int32_t gx, int32_t gy, double comm_weight,
                         int32_t itemsize, double wx, double wy,
                         int32_t* split_out, double* cost_out) {
  if (gx <= 0 || gy <= 0 || itemsize <= 0 || lays == nullptr ||
      wx <= 0.0 || wy <= 0.0)
    return 1;
  return chain_dp_impl(n, dims, dens, lays, static_cast<double>(gx),
                       static_cast<double>(gy), comm_weight,
                       static_cast<double>(itemsize), wx, wy, split_out,
                       cost_out);
}

}  // extern "C"
