// matrel_tpu native optimizer core: matrix-chain DP with sparsity-aware
// cost — the C++ equivalent of the reference's driver-side Catalyst
// optimizer hot loop (SURVEY.md §2 "Optimizer: matrix-chain DP"; §3.3).
//
// The reference runs this O(n³) interval DP on the Spark driver (JVM).
// For long chains the Python fallback (ir/chain.py) dominates planning
// time, so the planner calls into this library via ctypes when built
// (utils/native.py). Semantics mirror ir/chain.py + ir/stats.py exactly:
//
//   cost(i,j,s) = cost(i,s) + cost(s+1,j)
//               + 2 * rows(i) * cols(s) * cols(j) * d(i,s) * d(s+1,j)
//   d over an interval: matmul_density(d_left, d_right, k)
//                     = 1 - (1 - d_l*d_r)^k   (stable via expm1/log1p)
//
// Build: make -C native   →  libmatrel_opt.so
//
// C ABI only — consumed with ctypes, no pybind11 dependency.

#include <cmath>
#include <cstdint>
#include <vector>

namespace {

double matmul_density(double da, double db, double k) {
  double p = da * db;
  if (p <= 0.0) return 0.0;
  if (p >= 1.0) return 1.0;
  return -std::expm1(k * std::log1p(-p));
}

// Per-device ICI bytes of the cheapest MM strategy for (n×k)·(k×m) on a
// gx×gy mesh. MUST mirror ir/stats.py::comm_proxy (planner.comm_cost at
// the canonical 2d layout: no layout credits, no admissibility gates) —
// the equivalence is asserted by tests/test_native.py::
// test_comm_dp_native_matches_python.
double comm_proxy(double n, double k, double m, double da, double db,
                  double gx, double gy, double itemsize) {
  double p = gx * gy;
  if (p <= 1.0) return 0.0;
  double a_b = n * k * itemsize * da;
  double b_b = k * m * itemsize * db;
  double c_b = n * m * itemsize;
  double bmm_r = b_b * (p - 1.0) / p + (a_b / p) * (1.0 - 1.0 / gy);
  double bmm_l = a_b * (p - 1.0) / p + (b_b / p) * (1.0 - 1.0 / gx);
  double cpmm = (b_b / gy) * (gx - 1.0) / gx + (c_b / gx) * (gy - 1.0) / gy;
  double rmm = (a_b / gx) * (gy - 1.0) / gy + (b_b / gy) * (gx - 1.0) / gx;
  double best = bmm_r < bmm_l ? bmm_r : bmm_l;
  if (cpmm < best) best = cpmm;
  if (rmm < best) best = rmm;
  return best;
}

int chain_dp_impl(int32_t n, const int64_t* dims, const double* dens,
                  double gx, double gy, double comm_weight, double itemsize,
                  int32_t* split_out, double* cost_out) {
  if (n <= 0 || dims == nullptr || dens == nullptr || split_out == nullptr ||
      cost_out == nullptr)
    return 1;
  if (n == 1) {
    *cost_out = 0.0;
    return 0;
  }
  std::vector<double> cost(static_cast<size_t>(n) * n, 0.0);
  std::vector<double> density(static_cast<size_t>(n) * n, 1.0);
  for (int i = 0; i < n; ++i) density[i * n + i] = dens[i];

  for (int span = 2; span <= n; ++span) {
    for (int i = 0; i + span - 1 < n; ++i) {
      int j = i + span - 1;
      double best = -1.0;
      int best_s = i;
      double best_d = 1.0;
      for (int s = i; s < j; ++s) {
        double dl = density[i * n + s];
        double dr = density[(s + 1) * n + j];
        double rows = static_cast<double>(dims[i]);
        double mid = static_cast<double>(dims[s + 1]);
        double colsj = static_cast<double>(dims[j + 1]);
        double step = 2.0 * rows * mid * colsj * dl * dr;
        if (comm_weight > 0.0)
          step += comm_weight *
                  comm_proxy(rows, mid, colsj, dl, dr, gx, gy, itemsize);
        double total = cost[i * n + s] + cost[(s + 1) * n + j] + step;
        if (best < 0.0 || total < best) {
          best = total;
          best_s = s;
          best_d = matmul_density(dl, dr, mid);
        }
      }
      cost[i * n + j] = best;
      density[i * n + j] = best_d;
      split_out[i * n + j] = best_s;
    }
  }
  *cost_out = cost[0 * n + (n - 1)];
  return 0;
}

}  // namespace

extern "C" {

// dims: n+1 entries — operand i is dims[i] x dims[i+1]
// dens: n entries   — density of operand i (1.0 = dense)
// split_out: n*n row-major; split_out[i*n+j] = optimal split s for the
//            inclusive interval [i, j] (undefined for i >= j)
// cost_out:  total optimal FLOP cost of [0, n-1]
// returns 0 on success, nonzero on bad input
int matrel_chain_dp(int32_t n, const int64_t* dims, const double* dens,
                    int32_t* split_out, double* cost_out) {
  return chain_dp_impl(n, dims, dens, 1.0, 1.0, 0.0, 4.0, split_out,
                       cost_out);
}

// Comm-aware variant: step cost additionally pays
// comm_weight * comm_proxy(dims, densities, gx, gy, itemsize) —
// FLOP-equivalents of the cheapest collective bill on the gx×gy mesh.
int matrel_chain_dp_comm(int32_t n, const int64_t* dims, const double* dens,
                         int32_t gx, int32_t gy, double comm_weight,
                         int32_t itemsize, int32_t* split_out,
                         double* cost_out) {
  if (gx <= 0 || gy <= 0 || itemsize <= 0) return 1;
  return chain_dp_impl(n, dims, dens, static_cast<double>(gx),
                       static_cast<double>(gy), comm_weight,
                       static_cast<double>(itemsize), split_out, cost_out);
}

}  // extern "C"
