// Native SpMV plan builder (ops/spmv.py host-side layout).
//
// The blocked one-hot layout needs edges grouped by destination block with
// stable intra-block order — a counting-sort scatter, not a global argsort.
// numpy pays O(m log m) argsort + four fancy-indexed scatters (~3.4 s at
// 10M edges); this is two O(m) passes (~0.1 s).
//
// Pass 1 (matrel_spmv_counts): per-block edge counts — Python derives the
// capacity/refusal decisions from these (policy stays in Python, testable).
// Pass 2 (matrel_spmv_fill): scatter edges into the padded (nb, cap)
// tables in input order; edges past a block's capacity go to the overflow
// COO, stably sorted by row (segment_sum wants sorted ids).
//
// Slot order within a block differs from the numpy path (input order vs
// row-sorted) — the one-hot contraction is order-agnostic, so the
// contract (tests assert it) is equal spmv RESULTS, not byte-equal
// layouts. Sentinel convention matches: src = n_cols, off = 0, val = 0.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <vector>

extern "C" {

int matrel_spmv_counts(const int64_t* rows, int64_t m, int64_t block,
                       int64_t nb, int64_t* counts) {
    if (block <= 0 || nb <= 0) return -1;
    std::memset(counts, 0, sizeof(int64_t) * nb);
    for (int64_t e = 0; e < m; ++e) {
        // test rows[e] itself: truncating division maps (-block, 0) to 0,
        // which would sneak negatives past a `b < 0` guard
        if (rows[e] < 0) return -1;
        int64_t b = rows[e] / block;
        if (b >= nb) return -1;
        counts[b]++;
    }
    return 0;
}

// Returns the overflow edge count written, or -1 on error. vals may be
// null (edge weight 1.0). Output tables are (nb, cap) row-major.
int64_t matrel_spmv_fill(const int64_t* rows, const int64_t* cols,
                         const float* vals, int64_t m, int64_t n_cols,
                         int64_t block, int64_t nb, int64_t cap,
                         int32_t width,
                         int32_t* src8, int8_t* lane, int32_t* off,
                         float* val,
                         int64_t* ov_rows, int64_t* ov_cols, float* ov_vals,
                         int64_t ov_cap) {
    if (block <= 0 || nb <= 0 || cap <= 0 || width <= 0) return -1;
    const int64_t slots = nb * cap;
    const int32_t sentinel8 = static_cast<int32_t>(n_cols / width);
    const int8_t sentinel_lane = static_cast<int8_t>(n_cols % width);
    for (int64_t s = 0; s < slots; ++s) {
        src8[s] = sentinel8;
        lane[s] = sentinel_lane;
    }
    std::memset(off, 0, sizeof(int32_t) * slots);
    std::memset(val, 0, sizeof(float) * slots);

    std::vector<int64_t> next(nb, 0);
    std::vector<int64_t> ov_idx;
    for (int64_t e = 0; e < m; ++e) {
        const int64_t r = rows[e];
        if (r < 0 || cols[e] < 0) return -1;
        const int64_t b = r / block;
        if (b >= nb) return -1;
        const int64_t slot = next[b]++;
        if (slot >= cap) {
            ov_idx.push_back(e);
            continue;
        }
        const int64_t p = b * cap + slot;
        const int64_t c = cols[e];
        src8[p] = static_cast<int32_t>(c / width);
        lane[p] = static_cast<int8_t>(c % width);
        off[p] = static_cast<int32_t>(r % block);
        val[p] = vals ? vals[e] : 1.0f;
    }
    const int64_t n_ov = static_cast<int64_t>(ov_idx.size());
    if (n_ov > ov_cap) return -1;
    // stable sort by row (ties keep input order) — matches numpy's
    // stable argsort-by-row then slot>=cap selection
    std::stable_sort(ov_idx.begin(), ov_idx.end(),
                     [rows](int64_t a, int64_t b) {
                         return rows[a] < rows[b];
                     });
    for (int64_t i = 0; i < n_ov; ++i) {
        const int64_t e = ov_idx[i];
        ov_rows[i] = rows[e];
        ov_cols[i] = cols[e];
        ov_vals[i] = vals ? vals[e] : 1.0f;
    }
    return n_ov;
}

}  // extern "C"
