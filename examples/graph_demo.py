"""Graph analytics on element-sparse matrices — COOMatrix + PageRank.

The reference's PageRank workload (SURVEY.md §3.5) on the TPU-idiomatic
sparse path: the edge list compiles once into a blocked one-hot MXU SpMV
plan (ops/spmv.py), then 30 power-iteration rounds run as ONE jitted
fori_loop — no per-round shuffle, no host round trips.

Run: python examples/graph_demo.py         (single chip or CPU)
     JAX_PLATFORMS=cpu python examples/graph_demo.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from matrel_tpu import COOMatrix
from matrel_tpu.workloads.pagerank import pagerank_edges


def main():
    rng = np.random.default_rng(0)
    n, m = 50_000, 400_000
    src = rng.integers(0, n, m)
    dst = rng.integers(0, n, m)

    # -- element-sparse linear algebra through COOMatrix ------------------
    A = COOMatrix.from_edges(src, dst, shape=(n, n))
    print(f"adjacency: {A.shape}, nnz={A.nnz}, "
          f"plan padding ratio={A._get_plan().padding_ratio:.2f}")
    deg_out = np.asarray(A.matvec(np.ones(n, np.float32)))   # out-degrees
    deg_in = np.asarray(A.rmatvec(np.ones(n, np.float32)))   # in-degrees
    print(f"mean degree: out={deg_out.mean():.2f} in={deg_in.mean():.2f}")

    # two-hop reachability mass from a seed set, Aᵀ·(Aᵀ·s)
    seed = np.zeros(n, np.float32)
    seed[:10] = 1.0
    two_hop = np.asarray(A.rmatvec(A.rmatvec(seed)))
    print(f"two-hop mass from 10 seeds: {two_hop.sum():.0f} "
          f"(~{m/n:.0f}² × 10 expected)")

    # -- PageRank: 30 rounds in one jitted program ------------------------
    ranks = np.asarray(pagerank_edges(src, dst, n, rounds=30))
    top = np.argsort(ranks)[::-1][:5]
    print("top-5 nodes:", ", ".join(f"{i} ({ranks[i]:.2e})" for i in top))
    print(f"rank mass: {ranks.sum():.6f} (=1 up to fp)")


if __name__ == "__main__":
    main()
