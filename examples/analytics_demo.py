"""Matrix-analytics queries: triangle counting + all-pairs cosine
similarity with a thresholded similarity join.

Shows the round-3 workload families end-to-end:
  - trace(A·A·A)/6 through the chain/aggregate optimizer (also
    reachable as SQL: ``trace(A * A * A)``),
  - cosine similarity whose X·Xᵀ core takes the symmetric 2-pass
    bf16 Gram lowering under ``matmul_precision="high"``,
  - a σ-thresholded "similar pairs" count on the result.

Run: python examples/analytics_demo.py        (single chip or CPU)
     JAX_PLATFORMS=cpu python examples/analytics_demo.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from matrel_tpu.config import MatrelConfig
from matrel_tpu.relational import ops as R
from matrel_tpu.session import MatrelSession
from matrel_tpu.workloads import similarity, triangles

rng = np.random.default_rng(0)

sess = MatrelSession.builder().config(matmul_precision="high").get_or_create()

# -- triangles --------------------------------------------------------------
n = 256
a = (rng.random((n, n)) < 0.05).astype(np.float32)
a = np.triu(a, 1)
a = a + a.T
A = sess.from_numpy(a)
tri = triangles.triangle_count(A)
print(f"triangles: {tri:.0f} (oracle {triangles.triangles_numpy_oracle(a):.0f})")

sess.register("A", A)
tri_sql = sess.compute(sess.sql("trace(A * A * A)")).to_numpy()[0, 0] / 6
print(f"triangles via SQL: {tri_sql:.0f}")

# -- cosine similarity + thresholded join -----------------------------------
x = rng.standard_normal((512, 64)).astype(np.float32)
X = sess.from_numpy(x)
S = similarity.cosine_similarity_expr(X)
# similar pairs: entries of S above 0.8, counted (the n diagonal
# self-pairs cos(x_i, x_i) = 1 are included — subtract n for the
# off-diagonal count, as the print below notes)
sim_pairs = R.aggregate(
    R.select_entries(S, lambda v: v > 0.8), "count", "all")
cnt = sess.compute(sim_pairs).to_numpy()[0, 0]
oracle = similarity.cosine_similarity_numpy_oracle(x)
print(f"pairs with cos > 0.8: {cnt:.0f} "
      f"(oracle {np.count_nonzero(oracle > 0.8)}, incl. {len(x)} diagonal)")
