"""The heart of the reference: cost-based matrix-chain reordering.

MatRel's flagship optimization is the linear-algebra analogue of join-order
enumeration — an O(n³) interval DP over a multiply chain, with
sparsity-aware cost estimates (SURVEY.md §3.3). This demo builds a skewed
chain where evaluation order changes the FLOP count by ~50×, shows the
optimizer picking the cheap parenthesisation, and times both plans.

Run: python examples/chain_optimizer_demo.py
     JAX_PLATFORMS=cpu python examples/chain_optimizer_demo.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from matrel_tpu import MatrelConfig, MatrelSession


def flops_of(dims):
    (n, k), (_, m), (_, p) = dims
    left = 2 * n * k * m + 2 * n * m * p       # (A·B)·C
    right = 2 * k * m * p + 2 * n * k * p      # A·(B·C)
    return left, right


def main():
    sess = MatrelSession.builder().get_or_create()
    print(f"mesh: {dict(sess.mesh.shape)}")

    # A: 4096×64, B: 64×4096, C: 4096×64 — the DSL's natural left-assoc
    # order materialises a 4096² intermediate; right-assoc keeps every
    # intermediate 64-wide (160× fewer FLOPs)
    dims = [(4096, 64), (64, 4096), (4096, 64)]
    rng = np.random.default_rng(0)
    A, B, C = (sess.from_numpy(
        rng.standard_normal(d).astype(np.float32) / 64) for d in dims)
    expr = A.expr().multiply(B.expr()).multiply(C.expr())

    left, right = flops_of(dims)
    print(f"(A·B)·C costs {left/1e6:.0f} MFLOPs; "
          f"A·(B·C) costs {right/1e6:.0f} MFLOPs")

    print("\n--- optimizer explain (analyze=True: measured per-op ms "
          "next to the planner's strategy + ICI estimate) ---")
    print(sess.explain(expr, analyze=True))

    def compiled_flops(plan):
        arrays = [l.attrs["matrix"].data for l in plan.leaf_order]
        lowered = plan.jitted.lower(*arrays, *plan.extra_args)
        cost = lowered.compile().cost_analysis()
        # jax 0.4.x returns one dict per computation; modern jax a dict
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        return cost["flops"]

    def timed(plan, label):
        run = plan.bound_runner()
        float(np.asarray(run()).sum())       # warm + force
        t0 = time.perf_counter()
        for _ in range(20):
            out = run()
        s = float(np.asarray(out).sum())     # force completion
        dt = (time.perf_counter() - t0) / 20
        print(f"{label:>12}: {compiled_flops(plan)/1e6:7.0f} MFLOPs "
              f"compiled, {dt*1e3:7.3f} ms/exec  (checksum {s:+.4f})")
        return dt

    opt = sess.compile(expr)
    raw_cfg = MatrelConfig(chain_opt=False, rewrite_rules=False)
    from matrel_tpu.executor import compile_expr
    raw = compile_expr(expr, sess.mesh, raw_cfg)

    t_raw = timed(raw, "left-assoc")
    t_opt = timed(opt, "DP-reordered")
    ratio = compiled_flops(raw) / compiled_flops(opt)
    print(f"\nchain DP cut compiled FLOPs {ratio:.0f}x "
          f"(wall-clock {t_raw/t_opt:.1f}x here; small plans are "
          f"dispatch-bound on fast hosts — the FLOP ratio is what scales)")


if __name__ == "__main__":
    main()
