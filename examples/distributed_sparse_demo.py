"""Distributed sparse matrices: sharded tile stacks and sharded SpMV.

The single-chip sparse paths replicate the sparse operand; at pod scale
the operand itself must shard. This demo runs both scale-out plans on a
CPU-simulated 8-device mesh (the same code drives a real slice):

  1. BlockSparseMatrix.shard()      — tile stack cut into per-device
     output row ranges, one all_gather of the product rows (RMM-shaped)
  2. spmv.shard_plan + spmv_sharded — one-hot SpMV plan tables
     row-decomposed over the mesh (the PageRank shape)

Run:  python examples/distributed_sparse_demo.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import numpy as np                                    # noqa: E402
import jax                                            # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp                               # noqa: E402
from matrel_tpu.core import mesh as mesh_lib          # noqa: E402
from matrel_tpu.core.blockmatrix import BlockMatrix   # noqa: E402
from matrel_tpu.core.sparse import BlockSparseMatrix  # noqa: E402
from matrel_tpu.ops import spmv as spmv_lib           # noqa: E402


def main():
    mesh = mesh_lib.make_mesh()
    print(f"mesh: {dict(mesh.shape)} over {mesh.size} devices\n")
    rng = np.random.default_rng(0)

    # -- 1. sharded tile-stack SpMM -------------------------------------
    n, bs = 4096, 128
    a = np.zeros((n, n), np.float32)
    g = n // bs
    for f in rng.choice(g * g, size=g * g // 10, replace=False):
        bi, bj = divmod(int(f), g)
        a[bi*bs:(bi+1)*bs, bj*bs:(bj+1)*bs] = rng.standard_normal((bs, bs))
    d = rng.standard_normal((n, 64)).astype(np.float32)

    S = BlockSparseMatrix.from_numpy(a, block_size=bs, mesh=mesh)
    Ssh = S.shard()
    print(f"tile stack: {S.nnzb} tiles -> {Ssh.cap}/device "
          f"(padding {Ssh.padding_ratio:.2f}x)")
    out = Ssh.multiply(BlockMatrix.from_numpy(d, mesh=mesh)).to_numpy()
    err = np.abs(out - a @ d).max()
    print(f"sharded SpMM max err vs numpy: {err:.2e}\n")

    # -- 2. sharded one-hot SpMV (the PageRank shape) -------------------
    n_nodes, n_edges = 50_000, 400_000
    src = rng.integers(0, n_nodes, n_edges)
    dst = rng.integers(0, n_nodes, n_edges)
    w = rng.random(n_edges).astype(np.float32)
    plan = spmv_lib.build_spmv_plan(dst, src, w, n_nodes, n_nodes)
    plan_s = spmv_lib.shard_plan(plan, mesh)
    x = rng.standard_normal(n_nodes).astype(np.float32)
    y = np.asarray(spmv_lib.spmv_sharded(plan_s, jnp.asarray(x), mesh))
    oracle = np.zeros(n_nodes)
    np.add.at(oracle, dst, w * x[src])
    print(f"sharded SpMV ({n_edges} edges over {mesh.size} devices) "
          f"max err: {np.abs(y - oracle).max():.2e}")
    print("per-device table shard rows:",
          {s.data.shape[0] for s in plan_s.src8.addressable_shards})


if __name__ == "__main__":
    main()
