"""Layout-aware planning (round 5): the co-partitioning credit through
plan interiors.

The reference's partitioner-aware planner skips shuffles for
co-partitioned RDDs. The TPU rebuild goes further: `infer_layout`
propagates each node's output sharding bottom-up, so the credit fires
on CHAIN INTERIORS and joins — not just leaves — and the chain DP,
strategy choice, join schemes and autotune gate all read it. This demo
shows three visible effects on an 8-device mesh:

  1. a row-sharded input flips the strategy pick to broadcast-MM, and
     EXPLAIN prints the layouts next to the strategy provenance;
  2. a col-sharded MIDDLE operand flips a FLOP-tied chain's
     association — (A·B) consumes it in place;
  3. the same multiply picks a cheaper strategy as an interior than as
     a plan root (roots pay a re-lay to the canonical sharding).

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
         python examples/layout_aware_planning_demo.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    # append, don't setdefault: a pre-existing XLA_FLAGS would
    # otherwise leave a 1-device mesh where nothing here fires
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402

from matrel_tpu import executor  # noqa: E402
from matrel_tpu.core import mesh as mesh_lib  # noqa: E402
from matrel_tpu.core.blockmatrix import BlockMatrix  # noqa: E402
from matrel_tpu.parallel import planner  # noqa: E402


def main():
    import jax
    from jax.sharding import PartitionSpec as P

    jax.config.update("jax_platforms", "cpu")
    mesh = mesh_lib.make_mesh()
    print(f"mesh: {dict(mesh.shape)} over {mesh.size} devices\n")
    rng = np.random.default_rng(0)

    # 1) leaf + INTERIOR layout credit, visible in EXPLAIN ------------
    x = rng.standard_normal((1600, 512)).astype(np.float32)
    b = rng.standard_normal((512, 512)).astype(np.float32)
    c = rng.standard_normal((512, 512)).astype(np.float32)
    X_row = BlockMatrix.from_numpy(x, mesh=mesh,
                                   spec=P(tuple(mesh.axis_names), None))
    e = (X_row.expr()
         .multiply(BlockMatrix.from_numpy(b, mesh=mesh).expr())
         .multiply(BlockMatrix.from_numpy(c, mesh=mesh).expr()))
    plan = executor.compile_expr(e, mesh)
    print("row-sharded X through a chain — EXPLAIN shows layouts:")
    print(plan.explain())
    np.testing.assert_allclose(plan.run().to_numpy(), x @ b @ c,
                               rtol=2e-3, atol=2e-3)

    # 2) layout-aware chain DP: association flip ----------------------
    ca = rng.standard_normal((16, 512)).astype(np.float32)
    cb = rng.standard_normal((512, 512)).astype(np.float32)
    cc = rng.standard_normal((512, 16)).astype(np.float32)

    def assoc(spec):
        B = BlockMatrix.from_numpy(cb, mesh=mesh, spec=spec)
        pl = executor.compile_expr(
            BlockMatrix.from_numpy(ca, mesh=mesh).expr()
            .multiply(B.expr())
            .multiply(BlockMatrix.from_numpy(cc, mesh=mesh).expr()),
            mesh)
        left = pl.optimized.children[0].kind == "matmul"
        np.testing.assert_allclose(pl.run().to_numpy(), ca @ cb @ cc,
                                   rtol=2e-3, atol=2e-3)
        return "(A*B)*C" if left else "A*(B*C)"

    print("FLOP-tied chain, canonical B:  ", assoc(None))
    flipped = assoc(P(None, tuple(mesh.axis_names)))
    note = ("  <- (A*B) reads B in place"
            if flipped == "(A*B)*C" else
            "  (flip band is grid-specific; numerics verified)")
    print("same chain, B col-sharded:     ", flipped, note, "\n")

    # 3) root vs interior: the canonical-output re-lay charge ---------
    from matrel_tpu.ir.expr import leaf, matmul
    A_f = BlockMatrix.from_numpy(
        rng.standard_normal((1600, 512)).astype(np.float32), mesh=mesh)
    B_f = BlockMatrix.from_numpy(
        rng.standard_normal((512, 512)).astype(np.float32), mesh=mesh)
    node = matmul(leaf(A_f), leaf(B_f))
    interior, _ = planner.choose_strategy_ex(node, mesh)
    root, _ = planner.choose_strategy_ex(node, mesh, root_output=True)
    print(f"(1600x512)@(512x512) as interior: {interior}; as plan "
          f"root: {root}")
    print("(roots re-lay their output to the canonical sharding — a "
          "1D-emitting\n strategy pays that move, so the pick can "
          "legitimately differ)")


if __name__ == "__main__":
    main()
