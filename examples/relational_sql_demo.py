"""Relational queries over matrices: the σ/γ/⋈ surface plus SQL — the
MatRel-paper pattern 'join two matrices, filter entries, aggregate'.

Run: python examples/relational_sql_demo.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from matrel_tpu import MatrelSession
from matrel_tpu.relational import ops as R


def main():
    sess = MatrelSession.builder().get_or_create()
    rng = np.random.default_rng(1)
    a = rng.standard_normal((64, 64)).astype(np.float32)
    b = rng.standard_normal((64, 64)).astype(np.float32)
    A, B = sess.from_numpy(a), sess.from_numpy(b)
    sess.register("A", A)
    sess.register("B", B)

    # DSL: join on index, keep positive entries, count per row
    joined = R.join_on_index(A, B, lambda x, y: x * y)
    pos = R.select_entries(joined, lambda v: v > 0)
    counts = R.aggregate(pos, "count", "row").compute(sess)
    print("rows with most positive A⊙B entries:",
          np.argsort(-counts.to_numpy().ravel())[:5])

    # The same style of query through SQL
    e = sess.sql("SELECT rowsum(select(elemmult(A, B), 'v > 0'))")
    print("per-row positive mass (first 5):",
          sess.compute(e).to_numpy().ravel()[:5])

    # Aggregation pushdown in action: rowSum(A·B) runs as A·rowSum(B)
    expr = A.multiply(B).row_sum()
    print(expr.explain())

    # Streaming value join: structured predicate + merge keep the
    # (|A|, |B|) pair matrix VIRTUAL — the aggregate runs sort-based in
    # O((na+nb)·log nb), so this scales to millions of entries per side
    j = R.join_on_values(A, B, merge="mul", predicate="lt")
    per_entry = R.aggregate(j, "sum", "row").compute(sess)
    print("Σ merge over matches, first 5 A-entries:",
          per_entry.to_numpy().ravel()[:5])

    # ...and the same through SQL, with FROM validation and WHERE sugar
    q = sess.sql(
        "SELECT rowsum(joinvalue(A, B, 'mul', 'lt')) FROM A, B")
    print("SQL agrees:", np.allclose(sess.compute(q).to_numpy(),
                                     per_entry.to_numpy(), atol=1e-4))
    w = sess.sql("SELECT A .* B FROM A, B WHERE v > 1")
    print("elemmul + WHERE nonzeros:",
          int((sess.compute(w).to_numpy() != 0).sum()))


if __name__ == "__main__":
    main()
