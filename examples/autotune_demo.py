"""The closed autotune loop: measured strategy choice + provenance.

The reference's MatfastPlanner picks BMM/CPMM/RMM from a cost ESTIMATE
(SURVEY.md §3.2). On the XLA substrate, measuring is cheap — so with
``MatrelConfig(autotune=True)`` the planner times every admissible
strategy once per recurring shape class on-device (median-of-3 marginal
timing; ties are recorded as ties so noise never becomes a winner),
persists the table as JSON, and lets the measured winner override the
byte model. EXPLAIN then shows WHY each multiply got its strategy:
``strategy=cpmm[measured|model|override|default]``.

This demo runs the loop on the CPU mesh: first compile measures and
persists; a second session (fresh process-cache) inherits the table.

Run: JAX_PLATFORMS=cpu python examples/autotune_demo.py
"""

import os
import sys
import tempfile

# strategy choice is a MULTI-device concern: on one device the planner
# short-circuits to the local dot before the autotune path ever runs —
# simulate an 8-device mesh (no-op if the caller already set XLA_FLAGS)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from matrel_tpu import MatrelConfig, MatrelSession


def main():
    rng = np.random.default_rng(0)
    with tempfile.TemporaryDirectory() as d:
        table_path = os.path.join(d, "autotune_table.json")
        cfg = MatrelConfig(autotune=True, autotune_table_path=table_path)
        sess = MatrelSession(config=cfg)
        a = sess.from_numpy(rng.standard_normal((256, 256))
                            .astype(np.float32))
        b = sess.from_numpy(rng.standard_normal((256, 256))
                            .astype(np.float32))
        e = a.expr().multiply(b.expr())

        # first compile: the loop measures every admissible strategy for
        # this shape class and persists the result
        txt1 = sess.explain(e)
        print("first session: ", next(
            ln for ln in txt1.splitlines() if "strategy=" in ln).strip())

        from matrel_tpu.parallel import autotune
        table = autotune.load_table(table_path)
        for key, entry in table.items():
            times = {s: f"{t * 1e3:.3f} ms"
                     for s, t in sorted(entry["times"].items(),
                                        key=lambda kv: kv[1])}
            print(f"measured {key}: best={entry['best']} {times}")

        # a fresh session (cleared process cache = a new process)
        # inherits the persisted measurement — no re-measure
        autotune._CACHE.clear()
        sess2 = MatrelSession(config=cfg)
        a2 = sess2.from_numpy(rng.standard_normal((256, 256))
                              .astype(np.float32))
        b2 = sess2.from_numpy(rng.standard_normal((256, 256))
                              .astype(np.float32))
        txt = sess2.explain(a2.expr().multiply(b2.expr()))
        line = next(ln for ln in txt.splitlines() if "strategy=" in ln)
        print("second session:", line.strip())
        # provenance is either [measured] (a strategy won by >10%) or
        # [model] (the measurements tied — the byte model decides)
        assert "[measured]" in line or "[model]" in line


if __name__ == "__main__":
    main()
