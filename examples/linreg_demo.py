"""Normal-equations linear regression end-to-end — the reference's flagship
workload, through session + DSL + optimizer + jitted execution.

Run: python examples/linreg_demo.py        (single chip or CPU mesh)
     XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     JAX_PLATFORMS=cpu python examples/linreg_demo.py   (simulated mesh)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from matrel_tpu import MatrelSession
from matrel_tpu.workloads import linreg


def main():
    sess = MatrelSession.builder().get_or_create()
    print(f"mesh: {dict(sess.mesh.shape)}")

    rng = np.random.default_rng(0)
    n, k = 100_000, 64
    x = rng.standard_normal((n, k)).astype(np.float32)
    theta_true = rng.standard_normal((k, 1)).astype(np.float32)
    y = x @ theta_true + 0.01 * rng.standard_normal((n, 1)).astype(np.float32)

    X, Y = sess.from_numpy(x), sess.from_numpy(y)

    # Show the optimizer at work on the full expression
    expr = X.t().multiply(X)
    print(expr.explain())
    plan = sess.compile(expr)
    print("strategies/collectives:", plan.explain().splitlines()[-1])

    theta = np.asarray(linreg.fit(X, Y))
    err = np.linalg.norm(theta - theta_true) / np.linalg.norm(theta_true)
    print(f"relative parameter error: {err:.2e}")


if __name__ == "__main__":
    main()
